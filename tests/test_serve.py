"""HTTP serving front end (tools/serve.py): tokens over the wire match
solo DecodePipeline runs; prefix registration is reused across requests."""
import json
import os
import socket
import struct
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-gpt2"

pytestmark = pytest.mark.fleet      # spawns the server process


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port, path, obj, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _spawn_server(extra_args=()):
    """Start tools/serve.py on a free port; yield the port, then stop it
    (one copy of the spawn/readiness/teardown logic for every fixture)."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "-pt", "1,4,5,8", "--max-len", "48",
         "-t", "float32", "--port", str(port), *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server died: {proc.stdout.read()}")
        else:
            raise RuntimeError("server never came up")
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.fixture(scope="module")
def server():
    yield from _spawn_server()


@pytest.fixture(scope="module")
def solo_pipe():
    import jax

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    del jax
    total = registry.get_model_layers(MODEL)
    partition = [(1, 4), (5, 8)]
    params = []
    for i, (l, r) in enumerate(partition):
        _, p, _ = registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                                unroll=False)
        params.append(p)
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), partition, params, max_len=48)


def test_healthz_and_generate_matches_solo(server, solo_pipe):
    port = server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["ok"] and health["stages"] == 2
    assert health["speculative"] is False

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    got = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    want = np.asarray(solo_pipe.generate(np.asarray(ids), 6))
    np.testing.assert_array_equal(np.asarray(got), want)

    # stats surface in /healthz after work has flowed
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        stats = json.loads(resp.read())["stats"]
    assert stats["tokens"] >= 6 and stats["stage_steps"] > 0
    assert stats["active"] == 0 and stats["pending"] == 0

    # sampled request with a seed reproduces the solo rng discipline
    got_s = _post(port, "/generate", {"ids": ids, "new_tokens": 5,
                                      "temperature": 0.8, "seed": 7})["ids"]
    want_s = np.asarray(solo_pipe.generate(np.asarray(ids), 5,
                                           temperature=0.8, seed=7))
    np.testing.assert_array_equal(np.asarray(got_s), want_s)


def test_prefix_registration_reused(server, solo_pipe):
    port = server
    rng = np.random.default_rng(9)
    prefix = rng.integers(0, 100, size=(6,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    assert reg["len"] == 6
    handle = solo_pipe.precompute_prefix(np.asarray([prefix]))

    for seed in (0, 1):
        suffix = rng.integers(0, 100, size=(1, 4)).tolist()
        got = _post(port, "/generate",
                    {"ids": suffix, "new_tokens": 6,
                     "prefix_id": reg["prefix_id"]})["ids"]
        want = np.asarray(solo_pipe.generate(np.asarray(suffix), 6,
                                             prefix=handle))
        np.testing.assert_array_equal(np.asarray(got), want)

    # unknown prefix id is a clean 400
    try:
        _post(port, "/generate", {"ids": [[1, 2]], "new_tokens": 2,
                                  "prefix_id": "nope"})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_malformed_requests_clean_400(server):
    """Bad inputs never wedge the serving worker: empty prompts and
    unknown paths get clean JSON errors, and the service keeps serving."""
    port = server
    for bad in ({"ids": [], "new_tokens": 2},
                {"ids": [[]], "new_tokens": 2},
                {"ids": [[1, 2]], "new_tokens": 0}):
        try:
            _post(port, "/generate", bad)
            raise AssertionError(f"expected HTTP 400 for {bad}")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    # still alive and serving afterwards
    got = _post(port, "/generate", {"ids": [[5, 6, 7]], "new_tokens": 2})
    assert len(got["ids"][0]) == 5


@pytest.fixture(scope="module")
def spec_server():
    # the shared -pt matches solo_pipe: per-stage random init is seeded
    # per shard, so weights only match the oracle when partitions match
    yield from _spawn_server(("--draft-model", MODEL, "--gamma", "3"))


def test_speculative_serving_matches_plain(spec_server, solo_pipe):
    """--draft-model: requests with "speculative": true return tokens
    identical to plain greedy (here the draft IS the target, so every
    proposal is accepted); prefix registration feeds both models; the
    sampling composition is refused cleanly."""
    port = spec_server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["speculative"] is True
    rng = np.random.default_rng(13)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    plain = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    spec = _post(port, "/generate", {"ids": ids, "new_tokens": 6,
                                     "speculative": True})["ids"]
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))

    prefix = rng.integers(0, 100, size=(6,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    suffix = rng.integers(0, 100, size=(1, 4)).tolist()
    got = _post(port, "/generate",
                {"ids": suffix, "new_tokens": 5, "speculative": True,
                 "prefix_id": reg["prefix_id"]})["ids"]
    handle = solo_pipe.precompute_prefix(np.asarray([prefix]))
    want = np.asarray(solo_pipe.generate(np.asarray(suffix), 5,
                                         prefix=handle))
    np.testing.assert_array_equal(np.asarray(got), want)

    try:
        _post(port, "/generate", {"ids": ids, "new_tokens": 2,
                                  "speculative": True, "temperature": 0.7})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_speculative_unavailable_without_draft(server):
    """The plain server (no --draft-model) refuses speculative requests
    with a clean 400."""
    try:
        _post(server, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2,
                                    "speculative": True})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


@pytest.fixture(scope="module")
def stage_server():
    yield from _spawn_server(("--executor", "stage"))


def test_stage_executor_matches_solo_and_reports_workers(stage_server,
                                                         solo_pipe):
    """--executor stage: one worker thread per pipeline stage produces
    the same tokens as solo runs; /healthz reports per-worker stats."""
    port = stage_server
    rng = np.random.default_rng(17)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    got = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    want = np.asarray(solo_pipe.generate(np.asarray(ids), 6))
    np.testing.assert_array_equal(np.asarray(got), want)

    # prefix reuse flows through the stage executor too
    prefix = rng.integers(0, 100, size=(6,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    suffix = rng.integers(0, 100, size=(1, 4)).tolist()
    got_p = _post(port, "/generate", {"ids": suffix, "new_tokens": 5,
                                      "prefix_id": reg["prefix_id"]})["ids"]
    handle = solo_pipe.precompute_prefix(np.asarray([prefix]))
    want_p = np.asarray(solo_pipe.generate(np.asarray(suffix), 5,
                                           prefix=handle))
    np.testing.assert_array_equal(np.asarray(got_p), want_p)

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["executor"] == "stage"
    stats = health["stats"]
    assert len(stats["stage_steps"]) == 2        # one counter per worker
    assert all(s > 0 for s in stats["stage_steps"])
    assert len(stats["busy"]) == 2 and len(stats["queued"]) == 2
    assert stats["active"] == 0


def _stream_lines(port, obj, timeout=120):
    """POST a streaming /generate and return (lines, t_first, t_total):
    parsed x-ndjson lines plus client-side first-line/total wall times."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        t0 = time.monotonic()
        conn.request("POST", "/generate", json.dumps(obj),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "application/x-ndjson"
        lines, t_first = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            if t_first is None:
                t_first = time.monotonic() - t0
            lines.append(json.loads(line))
        return lines, t_first, time.monotonic() - t0
    finally:
        conn.close()


@pytest.mark.parametrize("fixture_name", ["server", "stage_server"])
def test_streaming_generate(fixture_name, request, solo_pipe):
    """"stream": true returns one x-ndjson line per decode step followed
    by a final line whose ids equal the non-streaming response; the
    final line records server-side first-token latency. Works on both
    executors."""
    port = request.getfixturevalue(fixture_name)
    rng = np.random.default_rng(21)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    n = 6
    lines, t_first, t_total = _stream_lines(
        port, {"ids": ids, "new_tokens": n, "stream": True})

    steps, final = lines[:-1], lines[-1]
    assert [ln["step"] for ln in steps] == list(range(n))
    assert final["steps"] == n
    assert final["first_token_ms"] is not None
    assert 0 < final["first_token_ms"] <= t_total * 1e3
    want = np.asarray(solo_pipe.generate(np.asarray(ids), n))
    np.testing.assert_array_equal(np.asarray(final["ids"]), want)
    # the streamed per-step tokens ARE the result's continuation columns
    streamed = np.stack([np.asarray(ln["tokens"]) for ln in steps], axis=1)
    np.testing.assert_array_equal(streamed, want[:, len(ids[0]):])


def test_streaming_eos_final_line_is_masked(server, solo_pipe):
    """With eos_token, streamed step lines carry raw picked tokens while
    the final line applies the pad-after-eos masking — byte-identical
    to the non-streaming result."""
    port = server
    rng = np.random.default_rng(23)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    plain = _post(port, "/generate",
                  {"ids": ids, "new_tokens": 6, "eos_token": 11})["ids"]
    lines, _, _ = _stream_lines(
        port, {"ids": ids, "new_tokens": 6, "eos_token": 11,
               "stream": True})
    np.testing.assert_array_equal(np.asarray(lines[-1]["ids"]),
                                  np.asarray(plain))
    assert len(lines) - 1 == lines[-1]["steps"]


@pytest.mark.parametrize("fixture_name", ["server", "stage_server"])
def test_concurrent_clients(fixture_name, request, solo_pipe):
    """Several clients hammering /generate concurrently (mixed plain,
    sampled, prefix, streaming) each get exactly their solo-run tokens —
    the executor isolation contract under real HTTP concurrency."""
    import threading
    port = request.getfixturevalue(fixture_name)
    rng = np.random.default_rng(29)
    prefix = rng.integers(0, 100, size=(6,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    handle = solo_pipe.precompute_prefix(np.asarray([prefix]))

    jobs = []
    for i in range(3):
        ids = rng.integers(0, 100, size=(1, 5 + i)).tolist()
        want = np.asarray(solo_pipe.generate(np.asarray(ids), 5,
                                             temperature=0.7, seed=i))
        jobs.append(({"ids": ids, "new_tokens": 5, "temperature": 0.7,
                      "seed": i}, want))
    suffix = rng.integers(0, 100, size=(1, 4)).tolist()
    jobs.append(({"ids": suffix, "new_tokens": 5,
                  "prefix_id": reg["prefix_id"]},
                 np.asarray(solo_pipe.generate(np.asarray(suffix), 5,
                                               prefix=handle))))
    stream_ids = rng.integers(0, 100, size=(2, 7)).tolist()
    stream_want = np.asarray(solo_pipe.generate(np.asarray(stream_ids), 5))

    results = {}

    def plain_client(i, req):
        results[i] = np.asarray(_post(port, "/generate", req)["ids"])

    def stream_client():
        lines, _, _ = _stream_lines(
            port, {"ids": stream_ids, "new_tokens": 5, "stream": True})
        results["stream"] = np.asarray(lines[-1]["ids"])

    threads = [threading.Thread(target=plain_client, args=(i, req))
               for i, (req, _) in enumerate(jobs)]
    threads.append(threading.Thread(target=stream_client))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive()
    for i, (_, want) in enumerate(jobs):
        np.testing.assert_array_equal(results[i], want)
    np.testing.assert_array_equal(results["stream"], stream_want)


def test_speculative_does_not_block_plain_requests(spec_server):
    """Round-4 advice: a long speculative generation must not serialize
    plain requests behind the service lock. Launch a long speculative
    request, then issue short plain requests while it runs; the plain
    requests complete well before the speculative one."""
    import threading
    port = spec_server
    rng = np.random.default_rng(31)
    long_ids = rng.integers(0, 100, size=(1, 8)).tolist()
    t_spec_done = [None]

    def spec_client():
        _post(port, "/generate", {"ids": long_ids, "new_tokens": 24,
                                  "speculative": True})
        t_spec_done[0] = time.monotonic()

    spec_thread = threading.Thread(target=spec_client)
    spec_thread.start()
    # issue plain requests while the speculative one is in flight; their
    # shapes were compiled by the earlier tests in this module, so they
    # are quick — without the dedicated spec lock they would all queue
    # behind the whole speculative generation
    done_before_spec = 0
    for i in range(3):
        ids = rng.integers(0, 100, size=(2, 8)).tolist()
        out = _post(port, "/generate", {"ids": ids, "new_tokens": 2})
        assert len(out["ids"][0]) == 10
        if t_spec_done[0] is None:
            done_before_spec += 1
    spec_thread.join(timeout=300)
    assert not spec_thread.is_alive()
    assert done_before_spec >= 1
    # healthz stayed responsive throughout and reports clean state
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["ok"]


def test_streaming_bad_request_still_400(server):
    """Streaming requests validate BEFORE the chunked headers commit:
    unknown prefix ids and invalid arguments return plain HTTP 400
    exactly like the non-streaming path."""
    port = server
    for bad in ({"ids": [[1, 2]], "new_tokens": 0, "stream": True},
                {"ids": [[1, 2]], "new_tokens": 2, "stream": True,
                 "prefix_id": "nope"},
                {"ids": [[]], "new_tokens": 2, "stream": True}):
        try:
            _post(port, "/generate", bad)
            raise AssertionError(f"expected HTTP 400 for {bad}")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400


def _tiny_pipe(partition=None, max_len=64):
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    total = registry.get_model_layers(MODEL)
    partition = partition or [(1, total)]
    params = []
    for i, (l, r) in enumerate(partition):
        _, p, _ = registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                                unroll=False)
        params.append(p)
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), partition, params, max_len=max_len)


def test_stage_executor_stop_wakes_blocked_submitter():
    """stop() must over-release the admission semaphore like _die() does:
    a submitter blocked in _slots.acquire() (pipeline full) wakes and
    raises instead of hanging forever (ADVICE.md r5).

    Deterministic by construction (this flaked under full-suite load
    when it was sleep-paced): "a" is known admitted once its FIRST token
    streams back (on_token fires from the last stage's worker), and "b"
    is known registered once it appears in the executor's live set —
    which happens BEFORE its semaphore wait, so stop()'s over-release
    reaches it whether it is already parked in acquire() or still on the
    way there (both paths re-check _dead and raise). "a" cannot complete
    early: its 44-token budget would need the whole pipeline to drain
    between two adjacent host steps here."""
    import threading

    import jax.numpy as jnp

    from pipeedge_tpu.parallel.batcher import StageWorkerExecutor

    ex = StageWorkerExecutor(_tiny_pipe(), max_active=1)
    errs = {}
    first_token = threading.Event()

    def client(rid, tokens, **kw):
        try:
            ex.submit(rid, jnp.zeros((1, 4), jnp.int32), tokens, **kw)
            ex.wait(rid, timeout=120)
        except RuntimeError as exc:
            errs[rid] = str(exc)

    # "a" holds the only admission slot with a long generation
    t_a = threading.Thread(target=client, args=("a", 44), daemon=True,
                           kwargs={"on_token":
                                   lambda s, t: first_token.set()})
    t_a.start()
    assert first_token.wait(timeout=120), "'a' never started decoding"
    # "b" heads for _slots.acquire (admission backpressure): it is in
    # the live set before it can block, so this wait is bounded by
    # thread scheduling only, not by any pipeline progress
    t_b = threading.Thread(target=client, args=("b", 2), daemon=True)
    t_b.start()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and "b" not in ex._live:
        time.sleep(0.01)
    assert "b" in ex._live, "'b' never reached admission"
    ex.stop()
    t_a.join(timeout=120)
    t_b.join(timeout=120)
    assert not t_a.is_alive() and not t_b.is_alive(), \
        "stop() left a submitter/waiter hanging"
    assert "in flight" in errs.get("a", "")
    # "b" raises either from the admission wake or from wait()
    assert "b" in errs


@pytest.mark.parametrize("executor", ["wave", "stage"])
def test_cancel_flag_completes_request_early(executor):
    """A set `cancel` flag finishes the request at its next pick with the
    tokens decoded so far, freeing executor capacity for live requests
    (the serve.py streaming-disconnect contract)."""
    import threading

    import jax.numpy as jnp

    from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,
                                               StageWorkerExecutor)

    pipe = _tiny_pipe()
    cancel = threading.Event()
    stop_after = 3
    seen = []

    def on_token(step, tok):
        seen.append(step)
        if step + 1 >= stop_after:
            cancel.set()

    ids = jnp.zeros((1, 4), jnp.int32)
    if executor == "stage":
        ex = StageWorkerExecutor(pipe, max_active=1)
        try:
            ex.submit("r", ids, 40, on_token=on_token, cancel=cancel)
            out = ex.wait("r", timeout=120)
        finally:
            ex.stop()
    else:
        batcher = ContinuousBatcher(pipe, max_active=1)
        batcher.submit("r", ids, 40, on_token=on_token, cancel=cancel)
        out = batcher.run()["r"]
    # prompt (4) + the tokens decoded before the cancel took effect —
    # far short of the 40-token cap
    assert out.shape[1] == 4 + stop_after
    assert len(seen) == stop_after


@pytest.fixture(scope="module")
def tight_server():
    """Stage executor with a SINGLE admission slot: a dead request that
    failed to free its slot would block every later request."""
    yield from _spawn_server(("--executor", "stage", "--max-active", "1"))


def test_streaming_disconnect_cancels_generation(tight_server):
    """A streaming client that disconnects mid-response must not keep
    decoding to the cap on a dead socket: the handler's write failure
    sets the request's cancel flag, the executor completes it early, and
    the admission slot frees (ADVICE.md r5). Verified via the server's
    cumulative token counter: the aborted 40-token request generates only
    a handful of tokens."""
    port = tight_server

    def healthz():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            return json.loads(resp.read())["stats"]

    tokens_before = healthz()["tokens"]
    new_tokens = 40
    body = json.dumps({"ids": [[1, 2, 3]], "new_tokens": new_tokens,
                       "stream": True}).encode()
    with socket.create_connection(("127.0.0.1", port), timeout=60) as sock:
        sock.sendall(
            b"POST /generate HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n"
            + body)
        # read until two step lines arrived (the stream is live), then
        # vanish with an RST so the server's next chunk write fails fast
        buf = b""
        deadline = time.monotonic() + 120
        while buf.count(b'"step"') < 2:
            assert time.monotonic() < deadline, f"no stream lines: {buf!r}"
            chunk = sock.recv(4096)
            assert chunk, f"server closed early: {buf!r}"
            buf += chunk
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    # the executor must finish the cancelled request and free its slot
    deadline = time.monotonic() + 120
    while healthz()["active"] > 0:
        assert time.monotonic() < deadline, \
            "cancelled request still holds its executor slot"
        time.sleep(0.1)
    generated = healthz()["tokens"] - tokens_before
    assert generated < new_tokens, (
        f"disconnected request decoded all {generated} tokens to the cap")
    # ... and the freed slot serves new requests normally
    out = _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    assert len(out["ids"][0]) == 5


def test_streaming_disconnect_storm_does_not_exhaust_slots(tight_server):
    """A BURST of streaming clients that all vanish mid-response (N well
    past max_active=1) must not strand admission slots: every cancelled
    request retires, `active` returns to 0, and a fresh request admits
    promptly instead of queueing behind ghosts."""
    port = tight_server

    def healthz():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            return json.loads(resp.read())["stats"]

    body = json.dumps({"ids": [[1, 2, 3]], "new_tokens": 40,
                       "stream": True}).encode()
    head = (b"POST /generate HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n")
    # open the whole storm first (they queue on the 1-slot executor),
    # then abort every socket with an RST — sockets still waiting for
    # admission AND the one mid-stream both disconnect
    socks = [socket.create_connection(("127.0.0.1", port), timeout=60)
             for _ in range(4)]
    try:
        for sock in socks:
            sock.sendall(head + body)
        # make sure at least one stream actually started before the storm
        # aborts (otherwise the test never exercises mid-flight cancel)
        buf, deadline = b"", time.monotonic() + 120
        while b'"step"' not in buf:
            assert time.monotonic() < deadline, f"no stream: {buf!r}"
            chunk = socks[0].recv(4096)
            assert chunk, f"server closed early: {buf!r}"
            buf += chunk
    finally:
        for sock in socks:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
            sock.close()
    # every ghost must retire and free its slot
    deadline = time.monotonic() + 120
    while healthz()["active"] > 0:
        assert time.monotonic() < deadline, (
            "disconnect storm stranded admission slots: active="
            f"{healthz()['active']}")
        time.sleep(0.1)
    # the server still serves: a fresh request admits through the single
    # slot the storm just vacated
    out = _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    assert len(out["ids"][0]) == 5


def test_stage_executor_stop_fails_live_waiters():
    """StageWorkerExecutor.stop() with requests in flight fails their
    waiters instead of hanging them (code-review finding)."""
    import threading

    import jax.numpy as jnp

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    from pipeedge_tpu.parallel.batcher import StageWorkerExecutor

    total = registry.get_model_layers(MODEL)
    _, params, _ = registry.module_shard_factory(MODEL, None, 1, total,
                                                 unroll=False)
    pipe = decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), [(1, total)], [params],
        max_len=64)
    ex = StageWorkerExecutor(pipe)
    errs = {}

    def client():
        ex.submit("r", jnp.zeros((1, 4), jnp.int32), 40)
        try:
            ex.wait("r", timeout=120)
        except RuntimeError as exc:
            errs["r"] = str(exc)

    t = threading.Thread(target=client)
    t.start()
    time.sleep(0.5)          # let the request enter the pipeline
    ex.stop()
    t.join(timeout=120)
    assert not t.is_alive()
    assert "in flight" in errs.get("r", "")


def test_degraded_window_503_retry_after_and_healthz(server):
    """The failover window (POST /degraded): /healthz names the dead rank,
    new work is answered 503 with a Retry-After header, and clearing the
    window restores normal service."""
    port = server
    try:
        assert _post(port, "/degraded", {"degraded": True, "dead_rank": 1,
                                         "retry_after": 2})["degraded"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["ok"]                      # degraded, not dead
        assert health["degraded"]["dead_rank"] == 1
        assert health["degraded"]["retry_after"] == 2
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
        assert err.value.code == 503
        assert err.value.headers["Retry-After"] == "2"
        body = json.loads(err.value.read())
        assert body["degraded"] and body["dead_rank"] == 1
        # prefix registration is admission too
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(port, "/prefix", {"ids": [1, 2, 3]})
        assert err.value.code == 503
    finally:
        _post(port, "/degraded", {"degraded": False})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["degraded"] is False
    out = _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    assert len(out["ids"][0]) == 5


def test_degraded_healing_healed_lifecycle(server):
    """The heal-aware window lifecycle: degraded -> healing (rank
    rejoined; still refusing with Retry-After, but /healthz distinguishes
    the phase) -> healed ({"degraded": false, "healed": true} clears the
    window AND counts on rejoined_ranks_total / /metrics)."""
    port = server

    def health():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            return json.loads(resp.read())

    before = health()["stats"]["rejoined_ranks_total"]
    try:
        _post(port, "/degraded", {"degraded": True, "dead_rank": 1,
                                  "retry_after": 2})
        assert health()["degraded"]["phase"] == "degraded"
        # the rank rejoined; the orchestrator flips the window to healing
        _post(port, "/degraded", {"degraded": True, "healing": True})
        h = health()
        assert h["degraded"]["phase"] == "healing"
        assert h["degraded"]["dead_rank"] == 1   # window state preserved
        # still refusing admission while the heal is in flight
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
        assert err.value.code == 503
    finally:
        # capacity restored: the healed close clears the window and bumps
        # the rejoined counter on BOTH surfaces
        _post(port, "/degraded", {"degraded": False, "healed": True,
                                  "rank": 1})
    h = health()
    assert h["degraded"] is False
    assert h["stats"]["rejoined_ranks_total"] == before + 1
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    assert "pipeedge_serve_rejoined_ranks_total" in text
    # a stray healing signal with no window open must not resurrect one
    _post(port, "/degraded", {"degraded": True, "healing": True})
    assert health()["degraded"] is False
    # and a plain (non-healed) clear does not count as a rejoin
    _post(port, "/degraded", {"degraded": True, "dead_rank": 2})
    _post(port, "/degraded", {"degraded": False})
    assert health()["stats"]["rejoined_ranks_total"] == before + 1
    out = _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    assert len(out["ids"][0]) == 5


def test_degraded_in_flight_request_replayed(solo_pipe):
    """A request that was IN FLIGHT when the failover window opened and
    whose executor fails during it is replayed once after recovery — the
    client sees one clean result, not the transient."""
    import threading

    from tools import serve as serve_mod

    svc = serve_mod._Service(solo_pipe, executor="wave")
    try:
        calls = []
        orig = svc._generate_once

        def flaky(ids, new_tokens, on_token, kw, rid=None):
            if not calls:
                calls.append(1)
                # the stage dies under this request: the service degrades
                # and the executor surfaces a transient failure
                svc.enter_degraded(dead_rank=1, retry_after=5.0)
                raise RuntimeError("stage died under this request")
            return orig(ids, new_tokens, on_token, kw, rid=rid)

        svc._generate_once = flaky
        recover = threading.Timer(0.5, svc.exit_degraded)
        recover.start()
        out = np.asarray(svc.generate([[5, 6, 7]], 3))
        recover.join()
        assert calls == [1]              # failed once, replayed once
        want = np.asarray(solo_pipe.generate(np.asarray([[5, 6, 7]]), 3))
        np.testing.assert_array_equal(out, want)
        # admission during a (re-entered) window still refuses new work
        svc.enter_degraded(dead_rank=2, retry_after=1.0)
        with pytest.raises(serve_mod.ServiceDegraded):
            svc.generate([[5, 6, 7]], 2)
        svc.exit_degraded()
    finally:
        svc.stop()


def _get_text(port, path, timeout=30):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as resp:
        return resp.headers.get("Content-Type", ""), resp.read().decode()


_PROM_LINE_RE = __import__("re").compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" [-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|Inf|NaN)$")


def test_metrics_endpoint_prometheus(server):
    """GET /metrics: Prometheus text format with the request-latency
    histogram, per-edge wire-byte counters, and the degraded/failover
    history — and /healthz's stats agree with it (one source of truth)."""
    port = server
    _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    ctype, text = _get_text(port, "/metrics")
    assert ctype.startswith("text/plain")
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert _PROM_LINE_RE.match(line), f"bad line: {line!r}"
    # request metrics present and live
    assert "# TYPE pipeedge_serve_request_latency_seconds histogram" in text
    assert "pipeedge_serve_request_latency_seconds_count" in text
    assert 'pipeedge_serve_requests_total{endpoint="/generate",' \
           'status="200"}' in text
    # per-edge wire-byte counters: the 2-stage server has one edge,
    # pre-declared so it renders even before traffic, nonzero after
    assert 'pipeedge_serve_edge_wire_bytes_total{edge="0->1"}' in text
    edge_val = [line for line in text.splitlines()
                if line.startswith('pipeedge_serve_edge_wire_bytes_total')]
    assert any(float(line.rsplit(" ", 1)[1]) > 0 for line in edge_val)
    # degraded/failover history starts clean and matches healthz
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        stats = json.loads(resp.read())["stats"]
    assert "pipeedge_serve_degraded_entered_total" in text
    assert {"degraded_entered_total", "failover_replays_total",
            "last_dead_rank"} <= set(stats)
    # open+close a degraded window: both surfaces move together
    _post(port, "/degraded", {"degraded": True, "dead_rank": 3,
                              "retry_after": 1})
    _post(port, "/degraded", {"degraded": False})
    _, text2 = _get_text(port, "/metrics")
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        stats2 = json.loads(resp.read())["stats"]
    assert stats2["degraded_entered_total"] == \
        stats["degraded_entered_total"] + 1
    assert stats2["last_dead_rank"] == 3
    assert "pipeedge_serve_last_dead_rank 3" in text2


# ---------------------------------------------------------------------------
# paged KV plane + disaggregated serving over HTTP (docs/SERVING.md)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def kv_server():
    """A paged, DISAGGREGATED server: --kv-pages turns admission into a
    token budget and the prefix trie on; --disaggregate wire routes
    every prompt pass through the prefill fleet + the v2-codec loopback
    socket ship path."""
    yield from _spawn_server(("--kv-pages", "48", "--kv-page-size", "4",
                              "--disaggregate", "wire"))


def test_kv_server_tokens_match_solo_and_budget_visible(kv_server,
                                                        solo_pipe):
    port = kv_server
    rng = np.random.default_rng(21)
    ids = rng.integers(0, 100, size=(1, 7)).tolist()
    got = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(solo_pipe.generate(np.asarray(ids), 6)))
    # sampled too: the pick happens decode-side from shipped logits,
    # so the rng discipline matches solo exactly
    got_s = _post(port, "/generate", {"ids": ids, "new_tokens": 5,
                                      "temperature": 0.9, "seed": 4})["ids"]
    np.testing.assert_array_equal(
        np.asarray(got_s),
        np.asarray(solo_pipe.generate(np.asarray(ids), 5,
                                      temperature=0.9, seed=4)))
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        serving = json.loads(resp.read())["serving"]
    kv = serving["kv"]
    assert kv["disaggregated"] and kv["pool"]["pages_total"] == 48
    # idle server: every page is back (free + trie-cached)
    assert kv["pool"]["pages_free"] \
        + kv["prefix"]["pages_cached"] == 48
    adm = serving["admission"]
    assert adm["token_budget"] == 48 * 4
    assert adm["tokens_free"] == adm["token_budget"]


def test_kv_server_prefix_id_rides_the_trie(kv_server, solo_pipe):
    """Paged mode /prefix: registration is a token list; generate with
    prefix_id returns suffix+continuation exactly like the dense handle
    contract, token-identical to a solo full-prompt run."""
    port = kv_server
    rng = np.random.default_rng(33)
    prefix = rng.integers(0, 100, size=(8,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    assert reg["len"] == 8
    suffix = rng.integers(0, 100, size=(1, 3)).tolist()
    full = np.asarray([prefix + suffix[0]])
    want = np.asarray(solo_pipe.generate(full, 5))[:, 8:]
    for _ in range(2):      # the second run reuses decode-side pages
        got = _post(port, "/generate",
                    {"ids": suffix, "new_tokens": 5,
                     "prefix_id": reg["prefix_id"]})["ids"]
        np.testing.assert_array_equal(np.asarray(got), want)
    # unknown prefix ids stay clean 400s in paged mode
    try:
        _post(port, "/generate", {"ids": suffix, "new_tokens": 2,
                                  "prefix_id": "nope"})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_kv_server_streaming_and_metrics(kv_server):
    port = kv_server
    body = json.dumps({"ids": [[1, 2, 3, 4, 5]], "new_tokens": 4,
                       "stream": True}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        lines = [json.loads(line) for line in
                 resp.read().decode().strip().splitlines()]
    assert lines[-1]["steps"] == 4 and len(lines) == 5
    assert len(lines[-1]["ids"][0]) == 5 + 4
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    for family in ("pipeedge_kv_pages", "pipeedge_kv_prefix_lookups_total",
                   "pipeedge_kv_ship_bytes_total",
                   "pipeedge_admission_tokens_free"):
        assert family in text, family
    # the wire ship path actually moved bytes
    wire_line = [line for line in text.splitlines()
                 if line.startswith('pipeedge_kv_ship_bytes_total{path="wire"}')]
    assert wire_line and float(wire_line[0].rsplit(" ", 1)[1]) > 0


def test_chunked_prefill_without_kv_pages_rejected_at_parse_time():
    """--chunked-prefill without --kv-pages is refused AT PARSE TIME,
    in milliseconds, with both flags named (ISSUE 16 satellite: chunk
    waves write prompt spans at an offset into a page table — dense
    slots have no such path)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "--chunked-prefill", "8",
         "--port", str(_free_port())],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
    took = time.monotonic() - t0
    assert proc.returncode == 2          # argparse usage error
    assert "--chunked-prefill" in proc.stderr \
        and "--kv-pages" in proc.stderr
    # parse-time means no model was built (interpreter startup only)
    assert took < 30, f"flag validation took {took:.1f}s — a model build?"


def test_prefill_budget_without_chunked_rejected_at_parse_time():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "--kv-pages", "8", "--prefill-budget", "4",
         "--port", str(_free_port())],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
    assert proc.returncode == 2
    assert "--prefill-budget" in proc.stderr \
        and "--chunked-prefill" in proc.stderr


def test_disaggregate_without_kv_pages_rejected_at_parse_time():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "--disaggregate", "process",
         "--port", str(_free_port())],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
    assert proc.returncode == 2
    assert "--disaggregate" in proc.stderr and "--kv-pages" in proc.stderr


# ---------------------------------------------------------------------------
# continuous batching + chunked prefill + paged speculative (ISSUE 16)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chunked_server():
    """Iteration-level scheduling on: prompts longer than 6 tokens run
    as 6-token chunk waves interleaved with decode steps, and the
    admission queue is re-driven at every step boundary."""
    yield from _spawn_server(("--kv-pages", "48", "--kv-page-size", "4",
                              "--chunked-prefill", "6", "--step-join"))


def test_chunked_server_tokens_match_solo(chunked_server, solo_pipe):
    """Long prompts served through chunked prefill are token-identical
    to the solo pipeline, and the healthz scheduler block proves chunk
    waves actually ran."""
    port = chunked_server
    rng = np.random.default_rng(57)
    for plen, nt, kw in ((20, 6, {}), (17, 5, {"temperature": 0.8,
                                               "seed": 3})):
        ids = rng.integers(0, 100, size=(1, plen)).tolist()
        got = _post(port, "/generate",
                    {"ids": ids, "new_tokens": nt, **kw})["ids"]
        np.testing.assert_array_equal(
            np.asarray(got),
            np.asarray(solo_pipe.generate(np.asarray(ids), nt, **kw)))
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        serving = json.loads(resp.read())["serving"]
    sched = serving["scheduler"]
    assert sched["chunked_prefill"] == 6 and sched["step_join"] is True
    assert sched["chunk_tokens"] == 6      # brownout lever unarmed
    assert sched["prefill_chunks"] >= 2    # both prompts chunked
    # idle: every page back (free + trie-cached)
    kv = serving["kv"]
    assert kv["pool"]["pages_free"] + kv["prefix"]["pages_cached"] == 48


@pytest.fixture(scope="module")
def spec_kv_server():
    """--draft-model + --kv-pages now compose (ISSUE 16): speculative
    draft/verify caches are paged onto the pool plane — the target's
    rounds reserve from the decode pool, the draft from its own."""
    yield from _spawn_server(("--kv-pages", "48", "--kv-page-size", "4",
                              "--draft-model", MODEL, "--gamma", "2"))


def test_speculative_over_paged_kv_matches_plain(spec_kv_server,
                                                 solo_pipe):
    port = spec_kv_server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["speculative"] is True
    rng = np.random.default_rng(41)
    ids = rng.integers(0, 100, size=(1, 7)).tolist()
    want = np.asarray(solo_pipe.generate(np.asarray(ids), 6))
    got = _post(port, "/generate", {"ids": ids, "new_tokens": 6,
                                    "speculative": True})["ids"]
    np.testing.assert_array_equal(np.asarray(got), want)
    # plain requests share the same pool and stay identical too
    got_p = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    np.testing.assert_array_equal(np.asarray(got_p), want)
    # idle: the speculative rounds returned every page they reserved
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        kv = json.loads(resp.read())["serving"]["kv"]
    assert kv["pool"]["pages_free"] + kv["prefix"]["pages_cached"] == 48
