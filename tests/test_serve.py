"""HTTP serving front end (tools/serve.py): tokens over the wire match
solo DecodePipeline runs; prefix registration is reused across requests."""
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-gpt2"

pytestmark = pytest.mark.fleet      # spawns the server process


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port, path, obj, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _spawn_server(extra_args=()):
    """Start tools/serve.py on a free port; yield the port, then stop it
    (one copy of the spawn/readiness/teardown logic for every fixture)."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "-pt", "1,4,5,8", "--max-len", "48",
         "-t", "float32", "--port", str(port), *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server died: {proc.stdout.read()}")
        else:
            raise RuntimeError("server never came up")
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.fixture(scope="module")
def server():
    yield from _spawn_server()


@pytest.fixture(scope="module")
def solo_pipe():
    import jax

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    del jax
    total = registry.get_model_layers(MODEL)
    partition = [(1, 4), (5, 8)]
    params = []
    for i, (l, r) in enumerate(partition):
        _, p, _ = registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                                unroll=False)
        params.append(p)
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), partition, params, max_len=48)


def test_healthz_and_generate_matches_solo(server, solo_pipe):
    port = server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["ok"] and health["stages"] == 2
    assert health["speculative"] is False

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    got = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    want = np.asarray(solo_pipe.generate(np.asarray(ids), 6))
    np.testing.assert_array_equal(np.asarray(got), want)

    # stats surface in /healthz after work has flowed
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        stats = json.loads(resp.read())["stats"]
    assert stats["tokens"] >= 6 and stats["stage_steps"] > 0
    assert stats["active"] == 0 and stats["pending"] == 0

    # sampled request with a seed reproduces the solo rng discipline
    got_s = _post(port, "/generate", {"ids": ids, "new_tokens": 5,
                                      "temperature": 0.8, "seed": 7})["ids"]
    want_s = np.asarray(solo_pipe.generate(np.asarray(ids), 5,
                                           temperature=0.8, seed=7))
    np.testing.assert_array_equal(np.asarray(got_s), want_s)


def test_prefix_registration_reused(server, solo_pipe):
    port = server
    rng = np.random.default_rng(9)
    prefix = rng.integers(0, 100, size=(6,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    assert reg["len"] == 6
    handle = solo_pipe.precompute_prefix(np.asarray([prefix]))

    for seed in (0, 1):
        suffix = rng.integers(0, 100, size=(1, 4)).tolist()
        got = _post(port, "/generate",
                    {"ids": suffix, "new_tokens": 6,
                     "prefix_id": reg["prefix_id"]})["ids"]
        want = np.asarray(solo_pipe.generate(np.asarray(suffix), 6,
                                             prefix=handle))
        np.testing.assert_array_equal(np.asarray(got), want)

    # unknown prefix id is a clean 400
    try:
        _post(port, "/generate", {"ids": [[1, 2]], "new_tokens": 2,
                                  "prefix_id": "nope"})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_malformed_requests_clean_400(server):
    """Bad inputs never wedge the serving worker: empty prompts and
    unknown paths get clean JSON errors, and the service keeps serving."""
    port = server
    for bad in ({"ids": [], "new_tokens": 2},
                {"ids": [[]], "new_tokens": 2},
                {"ids": [[1, 2]], "new_tokens": 0}):
        try:
            _post(port, "/generate", bad)
            raise AssertionError(f"expected HTTP 400 for {bad}")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    # still alive and serving afterwards
    got = _post(port, "/generate", {"ids": [[5, 6, 7]], "new_tokens": 2})
    assert len(got["ids"][0]) == 5


@pytest.fixture(scope="module")
def spec_server():
    # the shared -pt matches solo_pipe: per-stage random init is seeded
    # per shard, so weights only match the oracle when partitions match
    yield from _spawn_server(("--draft-model", MODEL, "--gamma", "3"))


def test_speculative_serving_matches_plain(spec_server, solo_pipe):
    """--draft-model: requests with "speculative": true return tokens
    identical to plain greedy (here the draft IS the target, so every
    proposal is accepted); prefix registration feeds both models; the
    sampling composition is refused cleanly."""
    port = spec_server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["speculative"] is True
    rng = np.random.default_rng(13)
    ids = rng.integers(0, 100, size=(2, 8)).tolist()
    plain = _post(port, "/generate", {"ids": ids, "new_tokens": 6})["ids"]
    spec = _post(port, "/generate", {"ids": ids, "new_tokens": 6,
                                     "speculative": True})["ids"]
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))

    prefix = rng.integers(0, 100, size=(6,)).tolist()
    reg = _post(port, "/prefix", {"ids": prefix})
    suffix = rng.integers(0, 100, size=(1, 4)).tolist()
    got = _post(port, "/generate",
                {"ids": suffix, "new_tokens": 5, "speculative": True,
                 "prefix_id": reg["prefix_id"]})["ids"]
    handle = solo_pipe.precompute_prefix(np.asarray([prefix]))
    want = np.asarray(solo_pipe.generate(np.asarray(suffix), 5,
                                         prefix=handle))
    np.testing.assert_array_equal(np.asarray(got), want)

    try:
        _post(port, "/generate", {"ids": ids, "new_tokens": 2,
                                  "speculative": True, "temperature": 0.7})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_speculative_unavailable_without_draft(server):
    """The plain server (no --draft-model) refuses speculative requests
    with a clean 400."""
    try:
        _post(server, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2,
                                    "speculative": True})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as exc:
        assert exc.code == 400
