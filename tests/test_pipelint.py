"""pipelint + lockdep tests (docs/STATIC_ANALYSIS.md).

One violating + one clean fixture per AST rule (the violating snippet
proves the rule FIRES, the clean one bounds its false positives),
suppression and baseline behavior, the CLI's exit-code contract, the
dcn protocol-table import self-check, and the runtime lock-order witness
(a real A->B / B->A cycle across two threads, condition-wait exemption,
blocking-under-lock detection).
"""
import json
import subprocess
import sys
import threading
import time

import pytest

from pipeedge_tpu.analysis import lint, lockdep


def run_on(tmp_path, source, name="snippet.py"):
    """Lint one source snippet; returns the list of fired rule ids."""
    p = tmp_path / name
    p.write_text(source)
    findings, errors, n = lint.run_lint([str(p)])
    assert not errors, errors
    assert n == 1
    return findings


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# -- PL101 lock-guarded-field-write --------------------------------------

PL101_BAD = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def reset(self):
        self._count = 0
"""

PL101_CLEAN = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def reset(self):
        with self._lock:
            self._count = 0

    def _drain_locked(self):
        self._count = 0    # _locked suffix: caller holds the lock
"""


def test_pl101_fires(tmp_path):
    findings = run_on(tmp_path, PL101_BAD)
    assert "PL101" in rule_ids(findings)
    (f,) = [f for f in findings if f.rule == "PL101"]
    assert "_count" in f.message and f.symbol == "C.reset"


def test_pl101_clean(tmp_path):
    assert "PL101" not in rule_ids(run_on(tmp_path, PL101_CLEAN))


# -- PL102 blocking-call-under-lock --------------------------------------

PL102_BAD = """
import time

class C:
    def flush(self, sock, payload):
        with self._lock:
            sock.sendall(payload)
            time.sleep(0.1)
"""

PL102_CLEAN = """
class C:
    def flush(self, sock, payload):
        with self._lock:
            data = dict(self._pending)     # snapshot under the lock
            meta = data.get("k", None)     # dict.get: not a queue wait
        sock.sendall(data)

    def wait_ready(self):
        with self._cond:
            self._cond.wait_for(lambda: self._ready)   # releases the lock

    def render(self, parts):
        with self._lock:
            return ", ".join(parts)        # str.join: not a thread join
"""


def test_pl102_fires(tmp_path):
    findings = [f for f in run_on(tmp_path, PL102_BAD) if f.rule == "PL102"]
    assert len(findings) == 2    # sendall + sleep
    assert any("sendall" in f.message for f in findings)
    assert any("sleep" in f.message for f in findings)


def test_pl102_clean(tmp_path):
    assert "PL102" not in rule_ids(run_on(tmp_path, PL102_CLEAN))


# -- PL201 thread-without-join-or-daemon ---------------------------------

PL201_BAD = """
import threading

def spawn():
    t = threading.Thread(target=work)
    t.start()
"""

PL201_CLEAN = """
import threading

class C:
    def start(self):
        self._bg = threading.Thread(target=work, daemon=True)
        self._bg.start()
        self._pump = threading.Thread(target=pump)
        self._pump.start()

    def close(self):
        self._pump.join()
"""


def test_pl201_fires(tmp_path):
    findings = run_on(tmp_path, PL201_BAD)
    assert "PL201" in rule_ids(findings)


def test_pl201_clean(tmp_path):
    assert "PL201" not in rule_ids(run_on(tmp_path, PL201_CLEAN))


def test_pl201_explicit_daemon_false_still_needs_join(tmp_path):
    # daemon=False is a CHOICE of a non-daemon thread, not an exemption
    src = """
import threading

def spawn():
    t = threading.Thread(target=work, daemon=False)
    t.start()
"""
    assert "PL201" in rule_ids(run_on(tmp_path, src))


def test_pl201_computed_daemon_value_is_owned(tmp_path):
    src = """
import threading

def spawn(flag):
    t = threading.Thread(target=work, daemon=flag)
    t.start()
"""
    assert "PL201" not in rule_ids(run_on(tmp_path, src))


def test_pl201_join_via_loop_variable(tmp_path):
    src = """
import threading

class C:
    def start(self):
        self._workers = [threading.Thread(target=work) for _ in range(4)]

    def stop(self):
        for w in self._workers:
            w.join()
"""
    assert "PL201" not in rule_ids(run_on(tmp_path, src))


# -- PL301 jit-in-loop ---------------------------------------------------

PL301_BAD = """
import jax

def run(microbatches):
    for mb in microbatches:
        fn = jax.jit(step)
        fn(mb)
"""

PL301_CLEAN = """
import jax

fn = jax.jit(step)

def run(microbatches):
    for mb in microbatches:
        fn(mb)

def make(variant):
    # a jit inside a nested def that the loop merely DEFINES is deferred
    for v in (1, 2):
        def build():
            return jax.jit(step)
"""


def test_pl301_fires(tmp_path):
    assert "PL301" in rule_ids(run_on(tmp_path, PL301_BAD))


def test_pl301_clean(tmp_path):
    assert "PL301" not in rule_ids(run_on(tmp_path, PL301_CLEAN))


# -- PL302 donated-arg-reuse ---------------------------------------------

PL302_BAD = """
import jax

fn = jax.jit(step, donate_argnums=(0,))

def run(payload):
    out = fn(payload)
    return payload.sum()
"""

PL302_CLEAN = """
import jax

fn = jax.jit(step, donate_argnums=(0,))
plain = jax.jit(step)

def run(payload):
    out = fn(payload)
    return out.sum()

def rebind(payload):
    payload = fn(payload)      # x = fn(x): the later read is the result
    return payload.sum()

def undonated(payload):
    out = plain(payload)
    return payload.sum()
"""


def test_pl302_fires(tmp_path):
    findings = run_on(tmp_path, PL302_BAD)
    assert "PL302" in rule_ids(findings)


def test_pl302_clean(tmp_path):
    assert "PL302" not in rule_ids(run_on(tmp_path, PL302_CLEAN))


# -- PL303 host-sync-in-dispatch-path ------------------------------------

PL303_BAD = """
import numpy as np

def dispatch_microbatch(out):
    host = np.asarray(out)      # D2H sync in the hot dispatch path
    return host
"""

PL303_CLEAN = """
import numpy as np

def dispatch_microbatch(out):
    return out                  # stays async

def readback(out):
    return np.asarray(out)      # syncs belong on the readback side
"""


def test_pl303_fires(tmp_path):
    assert "PL303" in rule_ids(run_on(tmp_path, PL303_BAD))


def test_pl303_clean(tmp_path):
    assert "PL303" not in rule_ids(run_on(tmp_path, PL303_CLEAN))


# -- PL401/PL402 protocol table ------------------------------------------

PL401_BAD = """
_MSG_A = 1
_MSG_B = 1

def dispatch(t):
    if t == _MSG_A:
        pass
    elif t == _MSG_B:
        pass
"""

PL402_BAD = """
_MSG_A = 1
_MSG_ORPHAN = 2

def dispatch(t):
    if t == _MSG_A:
        pass
"""

PL40X_CLEAN = """
_MSG_A = 1
_MSG_B = 2

def dispatch(t):
    if t == _MSG_A:
        pass
    elif t == _MSG_B:
        pass
"""


def test_pl401_fires(tmp_path):
    findings = run_on(tmp_path, PL401_BAD)
    assert "PL401" in rule_ids(findings)


def test_pl402_fires(tmp_path):
    findings = run_on(tmp_path, PL402_BAD)
    assert "PL402" in rule_ids(findings)
    (f,) = [f for f in findings if f.rule == "PL402"]
    assert "_MSG_ORPHAN" in f.message


def test_pl40x_clean(tmp_path):
    ids = rule_ids(run_on(tmp_path, PL40X_CLEAN))
    assert "PL401" not in ids and "PL402" not in ids


# -- PL403 missing-retry-after -------------------------------------------

PL403_BAD = """
class Handler:
    def reject(self):
        self.send_response(503)
        self.end_headers()
"""

PL403_CLEAN = """
class Handler:
    def reject(self):
        self.send_response(503)
        self.send_header("Retry-After", "5")
        self.end_headers()

    def shed(self, hint):
        self._send(503, {"error": "shed"},
                   extra_headers={"Retry-After": f"{hint:g}"})
"""


def test_pl403_fires(tmp_path):
    assert "PL403" in rule_ids(run_on(tmp_path, PL403_BAD))


def test_pl403_clean(tmp_path):
    assert "PL403" not in rule_ids(run_on(tmp_path, PL403_CLEAN))


def test_pl403_compliant_path_does_not_immunize_siblings(tmp_path):
    # one 503-with-Retry-After in a function must not silence a second,
    # bare 503 path beside it
    src = """
class Handler:
    def handle(self, shed):
        if shed:
            self.send_response(503)
            self.send_header("Retry-After", "5")
            self.end_headers()
            return
        do_other_work()
        check_more_state()
        and_some_more()
        if self.dead:
            self.send_response(503)
            self.end_headers()
"""
    findings = run_on(tmp_path, src)
    assert [f.rule for f in findings] == ["PL403"]
    assert findings[0].line > 10    # fired on the SECOND path only


# -- PL501 undeclared-metric-labels --------------------------------------

PL501_BAD = """
from pipeedge_tpu.telemetry import metrics as prom

_EVENTS = prom.REGISTRY.counter("events_total", "events by kind")

def record(kind):
    _EVENTS.inc(kind=kind)
"""

PL501_CLEAN = """
from pipeedge_tpu.telemetry import metrics as prom

_EVENTS = prom.REGISTRY.counter("events_total", "events by kind")
for kind in ("a", "b"):
    _EVENTS.declare(kind=kind)

_TOTAL = prom.REGISTRY.counter("plain_total", "unlabeled")

def record(kind):
    _EVENTS.inc(kind=kind)
    _TOTAL.inc()
"""


def test_pl501_fires(tmp_path):
    findings = run_on(tmp_path, PL501_BAD)
    assert "PL501" in rule_ids(findings)
    (f,) = [f for f in findings if f.rule == "PL501"]
    assert "events_total" in f.message


def test_pl501_clean(tmp_path):
    assert "PL501" not in rule_ids(run_on(tmp_path, PL501_CLEAN))


def test_pl501_declare_in_other_file(tmp_path):
    """The declare may live in a different module than the inc (the
    cross-file collect pass)."""
    (tmp_path / "metrics_def.py").write_text(PL501_BAD)
    (tmp_path / "declares.py").write_text("""
from metrics_def import _EVENTS
_EVENTS.declare(kind="a")
""")
    findings, errors, n = lint.run_lint([str(tmp_path)])
    assert not errors and n == 2
    assert "PL501" not in rule_ids(findings)


# -- PL502 unpaired-span -------------------------------------------------

PL502_BAD = """
from pipeedge_tpu import telemetry

def measure():
    s = telemetry.span("stage", "dispatch")
    s.__enter__()
"""

PL502_CLEAN = """
from pipeedge_tpu import telemetry

def measure():
    with telemetry.span("stage", "dispatch"):
        pass

def probe(rec):
    return rec.span("stage", "dispatch")   # factory return: the API itself
"""


PL502_REQUEST_BAD = """
from pipeedge_tpu import telemetry

def run_stage(req, i):
    # request-tagged span created outside `with`: the rid tag does not
    # exempt it — an error path still leaks the begin stamp
    s = telemetry.span("stage", f"exec{i}", stage=i, rid=str(req.rid))
    s.__enter__()
"""

PL502_REQUEST_CLEAN = """
from pipeedge_tpu import telemetry

def run_stage(req, i, trace):
    rid = trace.rid if trace is not None else None
    with telemetry.span("stage", "dispatch", stage=i, mb=0, rid=rid):
        pass
    # cross-thread request pairs belong to record(), which is not a span
    telemetry.record("serve", "admit:interactive", 0, 1, rid=rid)
"""


def test_pl502_fires(tmp_path):
    assert "PL502" in rule_ids(run_on(tmp_path, PL502_BAD))


def test_pl502_fires_on_request_tagged_span(tmp_path):
    assert "PL502" in rule_ids(run_on(tmp_path, PL502_REQUEST_BAD))


def test_pl502_clean(tmp_path):
    assert "PL502" not in rule_ids(run_on(tmp_path, PL502_CLEAN))


def test_pl502_clean_request_spans(tmp_path):
    assert "PL502" not in rule_ids(run_on(tmp_path, PL502_REQUEST_CLEAN))


# -- suppression + baseline ----------------------------------------------

def test_line_suppression(tmp_path):
    src = PL301_BAD.replace("fn = jax.jit(step)",
                            "fn = jax.jit(step)  # pipelint: disable=PL301")
    assert "PL301" not in rule_ids(run_on(tmp_path, src))


def test_line_suppression_is_rule_specific(tmp_path):
    src = PL301_BAD.replace("fn = jax.jit(step)",
                            "fn = jax.jit(step)  # pipelint: disable=PL999")
    assert "PL301" in rule_ids(run_on(tmp_path, src))


def test_file_suppression(tmp_path):
    src = "# pipelint: disable-file=PL301\n" + PL301_BAD
    assert "PL301" not in rule_ids(run_on(tmp_path, src))


def test_baseline_split_and_fingerprint_stability(tmp_path):
    findings = run_on(tmp_path, PL301_BAD)
    doc = json.loads(lint.Baseline.render(
        findings, {f.fingerprint: "grandfathered" for f in findings}))
    baseline = lint.Baseline(doc["findings"])
    # same code shifted to different lines: fingerprints still match
    shifted = run_on(tmp_path, "\n\n\n" + PL301_BAD, name="shifted.py")
    # (path differs -> fingerprint differs; use the same file instead)
    same = run_on(tmp_path, "# a comment\n" + PL301_BAD)
    new, baselined, stale = baseline.split(same)
    assert not new and baselined and not stale
    assert shifted[0].fingerprint != findings[0].fingerprint  # path-bound


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"findings": [
        {"fingerprint": "abc123", "rule": "PL301", "path": "x.py",
         "justification": "   "}]}))
    with pytest.raises(lint.LintError, match="justification"):
        lint.Baseline.load(str(p))


# -- CLI -----------------------------------------------------------------

def _cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tools.pipelint", *args],
        cwd=cwd, capture_output=True, text=True)


@pytest.mark.slow
def test_cli_exit_codes(tmp_path):
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = tmp_path / "bad.py"
    bad.write_text(PL301_BAD)
    clean = tmp_path / "clean.py"
    clean.write_text(PL301_CLEAN)
    r = _cli([str(clean), "--no-baseline"], repo_root)
    assert r.returncode == 0, r.stdout + r.stderr
    r = _cli([str(bad), "--no-baseline", "--json", "-"], repo_root)
    assert r.returncode == 1
    report = json.loads(r.stdout.splitlines()[0])
    assert report["counts_by_rule"].get("PL301") == 1
    assert not report["ok"]


@pytest.mark.slow
def test_cli_repo_tree_is_clean():
    """The acceptance gate: the shipped tree lints clean against the
    shipped (justified) baseline."""
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = _cli(["pipeedge_tpu", "tools", "runtime.py"], repo_root)
    assert r.returncode == 0, r.stdout + r.stderr


def test_rule_catalog_has_ten_distinct_rules():
    rules = lint.default_rules()
    ids = [r.id for r in rules]
    assert len(ids) == len(set(ids))
    assert len(ids) >= 10
    for r in rules:
        assert r.rationale and r.fix_hint and r.severity in (
            lint.SEVERITY_ERROR, lint.SEVERITY_WARNING)


# -- dcn protocol-table self-check ---------------------------------------

def test_dcn_protocol_self_check_passes():
    from pipeedge_tpu.comm import dcn
    dcn._check_protocol_table()    # the import already ran it; idempotent


def test_dcn_protocol_self_check_catches_collision(monkeypatch):
    from pipeedge_tpu.comm import dcn
    monkeypatch.setattr(dcn, "_MSG_FAKE_DUPE", dcn._MSG_TENSORS,
                        raising=False)
    with pytest.raises(AssertionError, match="collision"):
        dcn._check_protocol_table()


def test_dcn_protocol_self_check_catches_orphan(monkeypatch):
    from pipeedge_tpu.comm import dcn
    monkeypatch.setattr(dcn, "_MSG_FAKE_ORPHAN", 99, raising=False)
    with pytest.raises(AssertionError, match="no _reader_loop dispatch"):
        dcn._check_protocol_table()


# -- lockdep runtime witness ---------------------------------------------

def test_lockdep_witnesses_ab_ba_cycle():
    """Two threads taking the same pair of locks in opposite orders: the
    witness convicts the inversion WITHOUT needing the actual deadlock
    interleaving (the threads run sequentially here)."""
    st = lockdep.LockdepState()
    a = lockdep.TrackedLock(st, "A")
    b = lockdep.TrackedLock(st, "B")

    def fwd():
        with a:
            with b:
                pass

    def rev():
        with b:
            with a:
                pass

    for target in (fwd, rev):
        t = threading.Thread(target=target)
        t.start()
        t.join()
    cycles = st.cycles()
    assert cycles == [["A", "B"]]
    witnesses = st.edge_witnesses(cycles[0])
    held = {(w["held"], w["acquired"]) for w in witnesses}
    assert held == {("A", "B"), ("B", "A")}
    rep = st.report()
    assert rep["cycles"] == [["A", "B"]] and rep["threads"] == 2


def test_lockdep_duplicate_fingerprints_are_occurrence_indexed(tmp_path):
    # two identical violations in one function: distinct fingerprints, so
    # a baseline entry for the first never grandfathers the second
    src = """
import threading

class C:
    def send_twice(self):
        with self._lock:
            self._sock.sendall(b"a")
            self._sock.sendall(b"a")
"""
    findings = [f for f in run_on(tmp_path, src) if f.rule == "PL102"]
    assert len(findings) == 2
    fps = [f.fingerprint for f in findings]
    assert len(set(fps)) == 2
    assert fps[1] == fps[0] + "#2"
    bl = lint.Baseline([{"fingerprint": fps[0], "justification": "first"}])
    new, baselined, _ = bl.split(findings)
    assert len(baselined) == 1 and len(new) == 1
    assert new[0].fingerprint == fps[1]


def test_lockdep_two_instances_of_one_name_self_edge():
    """Nesting two INSTANCES of one lock site is the rank-N deadlock
    shape (thread 1: a->b, thread 2: b->a, same site): the name-folded
    graph records a self-edge and convicts it as a cycle."""
    st = lockdep.LockdepState()
    a = lockdep.TrackedLock(st, "pool")
    b = lockdep.TrackedLock(st, "pool")
    with a:
        with b:
            pass
    assert st.cycles() == [["pool"]]


def test_lockdep_reentrant_same_instance_is_not_a_cycle():
    st = lockdep.LockdepState()
    r = lockdep.TrackedRLock(st, "reent")
    with r:
        with r:
            pass
    assert st.cycles() == []


def test_lockdep_consistent_order_is_clean():
    st = lockdep.LockdepState()
    a = lockdep.TrackedLock(st, "A")
    b = lockdep.TrackedLock(st, "B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert st.cycles() == []


def test_lockdep_blocking_under_lock_detected():
    prev = lockdep.state()
    st = lockdep.enable(lockdep.LockdepState())
    try:
        lk = lockdep.TrackedLock(st, "L")
        time.sleep(0.001)          # no lock held: clean
        with lk:
            time.sleep(0.001)      # held: violation
        rep = st.report()
        assert len(rep["blocking_violations"]) == 1
        v = rep["blocking_violations"][0]
        assert v["held"] == ["L"] and "sleep" in v["call"]
    finally:
        if prev is not None:
            lockdep.enable(prev)
        else:
            lockdep.disable()


def test_lockdep_condition_wait_releases_held_stack():
    """Condition.wait parks the thread but RELEASES the lock: the witness
    must not call that a blocking-under-lock violation."""
    prev = lockdep.state()
    st = lockdep.enable(lockdep.LockdepState())
    try:
        cond = threading.Condition(lockdep.TrackedRLock(st, "C"))
        done = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                done.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            assert st.held() == ("C",)
            cond.notify_all()
        t.join(timeout=5)
        assert done == [True]
        assert st.held() == ()
        # the waiter's park must not be recorded as held-across-blocking
        rep = st.report()
        assert all(v["held"] != ["C"] or "sleep" in v["call"]
                   for v in rep["blocking_violations"])
        assert rep["cycles"] == []
    finally:
        if prev is not None:
            lockdep.enable(prev)
        else:
            lockdep.disable()


def test_lockdep_dump_appends_json_lines(tmp_path):
    st = lockdep.LockdepState()
    with lockdep.TrackedLock(st, "X"):
        pass
    out = tmp_path / "lockdep.json"
    st.dump(str(out))
    st.dump(str(out))
    lines = out.read_text().splitlines()
    assert len(lines) == 2
    rep = json.loads(lines[0])
    assert rep["locks"] == ["X"] and rep["cycles"] == []


def test_make_lock_factories_track_when_enabled():
    from pipeedge_tpu.utils import threads
    prev = lockdep.state()
    st = lockdep.enable(lockdep.LockdepState())
    try:
        lk = threads.make_lock("t.lock")
        assert isinstance(lk, lockdep.TrackedLock)
        cond = threads.make_condition("t.cond")
        with cond:
            pass
        with lk:
            pass
        assert "t.lock" in st.report()["locks"]
        assert "t.cond" in st.report()["locks"]
    finally:
        if prev is not None:
            lockdep.enable(prev)
        else:
            lockdep.disable()
    if prev is None:
        # witness off again: the factory hands back a plain stdlib lock
        assert isinstance(threads.make_lock("plain"),
                          type(threading.Lock()))
