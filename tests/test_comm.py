"""Comm context lifecycle tests.

Mirrors the reference's only comm tests — world_size=1 context init/shutdown
and context-manager reuse (test/comm/p2p/test_context.py:23-40,
test/comm/rpc/test_context.py:13-29) — plus command-plane delivery, which the
reference never tests.
"""
import threading
import time

from pipeedge_tpu.comm import (CMD_SCHED, CMD_STOP, CommandPlane, DistContext,
                               MultiHostContext, SliceContext)


def test_dist_context_lifecycle():
    ctx = DistContext(world_size=1, rank=0)
    assert not ctx.initialized
    ctx.init()
    assert ctx.initialized
    ctx.shutdown()
    assert not ctx.initialized
    # reusable as context manager (reference test_context.py:34-40)
    with ctx:
        assert ctx.initialized
    with ctx:
        assert ctx.initialized
    assert not ctx.initialized


def test_slice_context_devices_and_commands():
    got = []
    event = threading.Event()

    def handler(cmd, payload):
        got.append((cmd, payload))
        event.set()

    with SliceContext(cmd_handler=handler) as ctx:
        assert ctx.world_size >= 1
        assert len(ctx.devices) == ctx.world_size
        ctx.cmd_broadcast(CMD_SCHED, ((1, 24), (25, 48)))
        assert event.wait(timeout=5)
    assert got == [(CMD_SCHED, ((1, 24), (25, 48)))]


def test_multihost_single_process_noop():
    with MultiHostContext("127.0.0.1:0", num_processes=1, process_id=0) as ctx:
        assert ctx.initialized
        assert ctx.world_size == 1


def test_command_plane_ordering_and_stop():
    got = []
    plane = CommandPlane(lambda cmd, p: got.append(cmd))
    plane.start()
    for cmd in (CMD_SCHED, CMD_SCHED, CMD_STOP):
        plane.publish(cmd)
    deadline = time.monotonic() + 5
    while len(got) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    plane.stop()
    assert got == [CMD_SCHED, CMD_SCHED, CMD_STOP]
    plane.stop()  # idempotent
