"""Comm context lifecycle tests.

Mirrors the reference's only comm tests — world_size=1 context init/shutdown
and context-manager reuse (test/comm/p2p/test_context.py:23-40,
test/comm/rpc/test_context.py:13-29) — plus command-plane delivery, which the
reference never tests.
"""
import threading
import time

from pipeedge_tpu.comm import (CMD_SCHED, CMD_STOP, CommandPlane, DistContext,
                               MultiHostContext, SliceContext)


def test_dist_context_lifecycle():
    ctx = DistContext(world_size=1, rank=0)
    assert not ctx.initialized
    ctx.init()
    assert ctx.initialized
    ctx.shutdown()
    assert not ctx.initialized
    # reusable as context manager (reference test_context.py:34-40)
    with ctx:
        assert ctx.initialized
    with ctx:
        assert ctx.initialized
    assert not ctx.initialized


def test_slice_context_devices_and_commands():
    got = []
    event = threading.Event()

    def handler(cmd, payload):
        got.append((cmd, payload))
        event.set()

    with SliceContext(cmd_handler=handler) as ctx:
        assert ctx.world_size >= 1
        assert len(ctx.devices) == ctx.world_size
        ctx.cmd_broadcast(CMD_SCHED, ((1, 24), (25, 48)))
        assert event.wait(timeout=5)
    assert got == [(CMD_SCHED, ((1, 24), (25, 48)))]


def test_multihost_single_process_noop():
    with MultiHostContext("127.0.0.1:0", num_processes=1, process_id=0) as ctx:
        assert ctx.initialized
        assert ctx.world_size == 1


def test_command_plane_ordering_and_stop():
    got = []
    plane = CommandPlane(lambda cmd, p: got.append(cmd))
    plane.start()
    for cmd in (CMD_SCHED, CMD_SCHED, CMD_STOP):
        plane.publish(cmd)
    deadline = time.monotonic() + 5
    while len(got) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    plane.stop()
    assert got == [CMD_SCHED, CMD_SCHED, CMD_STOP]
    plane.stop()  # idempotent


def test_command_plane_stop_drains_pending():
    # A command published right before stop() must still be delivered.
    got = []
    plane = CommandPlane(lambda cmd, p: got.append(cmd))
    plane.start()
    plane.publish(CMD_SCHED)
    plane.publish(CMD_STOP)
    plane.stop()
    assert got == [CMD_SCHED, CMD_STOP]


def test_command_plane_handler_exception_keeps_dispatching():
    got = []

    def handler(cmd, payload):
        got.append(cmd)
        if cmd == CMD_SCHED:
            raise KeyError("malformed schedule payload")

    plane = CommandPlane(handler)
    plane.start()
    plane.publish(CMD_SCHED)  # raises inside handler
    plane.publish(CMD_STOP)  # must still be delivered
    plane.stop()
    assert got == [CMD_SCHED, CMD_STOP]


def test_command_plane_stop_from_handler():
    # A handler may react to CMD_STOP by stopping the plane (the reference's
    # CMD_STOP semantics, runtime.py:408-410); the dispatch thread must not
    # try to join itself, and queued commands before the cutoff still arrive.
    got = []
    plane = CommandPlane(None)

    def handler(cmd, payload):
        got.append(cmd)
        if cmd == CMD_STOP:
            plane.stop()

    plane._handler = handler
    # publish BEFORE start so both commands deterministically precede the
    # handler's stop() cutoff (held commands are delivered at start)
    plane.publish(CMD_STOP)
    plane.publish(CMD_SCHED)
    plane.start()
    deadline = time.monotonic() + 5
    while len(got) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got == [CMD_STOP, CMD_SCHED]
    # plane is stopped and restartable
    plane.start()
    plane.publish(CMD_SCHED)
    plane.stop()
    assert got == [CMD_STOP, CMD_SCHED, CMD_SCHED]


def test_command_plane_concurrent_stop():
    plane = CommandPlane(lambda cmd, p: None)
    plane.start()
    errors = []

    def stopper():
        try:
            plane.stop()
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=stopper) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_command_plane_publish_while_stopped_held_for_next_start():
    got = []
    plane = CommandPlane(lambda cmd, p: got.append(cmd))
    plane.publish(CMD_SCHED)  # plane never started yet
    plane.start()
    plane.stop()  # drains: delivers the held command
    assert got == [CMD_SCHED]


def test_command_plane_restart_does_not_replay():
    got = []
    plane = CommandPlane(lambda cmd, p: got.append(cmd))
    plane.start()
    plane.publish(CMD_SCHED)
    plane.stop()
    # restart: nothing stale may fire into the new session
    plane.start()
    plane.publish(CMD_STOP)
    plane.stop()
    assert got == [CMD_SCHED, CMD_STOP]
