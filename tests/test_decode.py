"""KV-cache pipelined decoding vs HF greedy generation (GPT-2 family)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import gpt2 as gpt2_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.parallel import decode  # noqa: E402

TINY = dict(hidden_size=32, num_hidden_layers=3, num_attention_heads=4,
            intermediate_size=64)


@pytest.fixture(scope="module")
def gpt2_setup():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(n_embd=32, n_layer=3, n_head=4, n_inner=64,
                        vocab_size=100, n_positions=64)
    torch.manual_seed(7)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = TransformerConfig(model_type="gpt2", **TINY, layer_norm_eps=1e-5,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    return cfg, weights, model


def _stage_params(cfg, partition, weights):
    total = 4 * cfg.num_hidden_layers
    return [gpt2_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in partition]


@pytest.mark.parametrize("partition", [
    [(1, 12)],
    [(1, 4), (5, 12)],
    [(1, 4), (5, 8), (9, 12)],
])
@pytest.mark.slow
def test_greedy_matches_hf_generate(gpt2_setup, partition):
    """Pipelined KV-cache greedy decode == HF generate(do_sample=False),
    token for token, for 1..3 stage partitions."""
    cfg, weights, model = gpt2_setup
    pipe = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), max_len=32)
    ids = np.asarray(
        np.random.default_rng(21).integers(0, 100, size=(3, 7)), np.int64)
    got = np.asarray(pipe.generate(ids, new_tokens=8))
    with torch.no_grad():
        expected = model.generate(
            torch.from_numpy(ids), max_new_tokens=8, do_sample=False,
            pad_token_id=0).numpy()
    np.testing.assert_array_equal(got, expected)


def test_decode_matches_teacher_forcing(gpt2_setup):
    """Step-by-step cached logits == full-sequence forward logits."""
    cfg, weights, _ = gpt2_setup
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = gpt2_mod.load_params(cfg, sc, weights)
    pre, dec = decode.make_stage_fns(gpt2_mod.FAMILY, cfg, sc)
    ids = jnp.asarray(
        np.random.default_rng(5).integers(0, 100, size=(2, 10)), jnp.int32)
    cache = decode.init_cache(cfg, cfg.num_hidden_layers, 2, 16)
    params = dict(params)
    params["blocks"] = decode.stage_blocks(params)

    from pipeedge_tpu.models.shard import make_shard_fn
    full = np.asarray(make_shard_fn(gpt2_mod.FAMILY, cfg, sc)(params,
                                                              ids))
    got, cache = pre(params, ids[:, :6], cache)
    np.testing.assert_allclose(np.asarray(got), full[:, :6], rtol=2e-5,
                               atol=2e-5)
    for t in range(6, 10):
        got, cache = dec(params, ids[:, t:t + 1], cache, t)
        np.testing.assert_allclose(np.asarray(got)[:, 0], full[:, t],
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_int8_kv_cache_close_to_exact(gpt2_setup):
    """int8-quantized KV cache (QuantPipe idea applied to decode): cached
    step logits stay close to the exact full-sequence forward."""
    cfg, weights, _ = gpt2_setup
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = dict(gpt2_mod.load_params(cfg, sc, weights))
    params["blocks"] = decode.stage_blocks(params)
    pre, dec = decode.make_stage_fns(gpt2_mod.FAMILY, cfg, sc)
    ids = jnp.asarray(
        np.random.default_rng(6).integers(0, 100, size=(2, 10)), jnp.int32)
    cache = decode.init_cache(cfg, cfg.num_hidden_layers, 2, 16, cache_bits=8)
    assert cache["k"].dtype == jnp.int8

    from pipeedge_tpu.models.shard import make_shard_fn
    full = np.asarray(make_shard_fn(gpt2_mod.FAMILY, cfg, sc)(params, ids))
    got, cache = pre(params, ids[:, :6], cache)
    np.testing.assert_allclose(np.asarray(got), full[:, :6], rtol=0.1,
                               atol=0.05)
    for t in range(6, 10):
        got, cache = dec(params, ids[:, t:t + 1], cache, t)
        np.testing.assert_allclose(np.asarray(got)[:, 0], full[:, t],
                                   rtol=0.1, atol=0.05)

    with pytest.raises(ValueError, match="cache_bits"):
        decode.init_cache(cfg, 2, 1, 8, cache_bits=4)


@pytest.mark.slow
def test_sampling_and_step_callback(gpt2_setup):
    """Temperature sampling: deterministic per seed, varies across seeds,
    stays in-vocab; temperature=0 equals greedy; callback fires per step."""
    cfg, weights, _ = gpt2_setup
    partition = [(1, 12)]
    pipe = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), max_len=32)
    ids = np.asarray(
        np.random.default_rng(41).integers(0, 100, size=(2, 6)), np.int64)
    steps = []
    greedy = np.asarray(pipe.generate(
        ids, 8, temperature=0.0, step_callback=lambda s, t: steps.append(s)))
    assert steps == list(range(8))
    greedy2 = np.asarray(pipe.generate(ids, 8))
    np.testing.assert_array_equal(greedy, greedy2)
    s_a = np.asarray(pipe.generate(ids, 8, temperature=0.9, seed=1))
    s_a2 = np.asarray(pipe.generate(ids, 8, temperature=0.9, seed=1))
    s_b = np.asarray(pipe.generate(ids, 8, temperature=0.9, seed=2))
    np.testing.assert_array_equal(s_a, s_a2)
    assert not np.array_equal(s_a, s_b)
    assert s_a[:, 6:].min() >= 0 and s_a[:, 6:].max() < 100
    # top-k=1 collapses sampling to greedy regardless of temperature
    top1 = np.asarray(pipe.generate(ids, 8, temperature=0.9, top_k=1, seed=3))
    np.testing.assert_array_equal(top1, greedy)


@pytest.mark.slow
def test_beam_search_matches_oracle(gpt2_setup):
    """generate_beam == a step-by-step numpy beam search over full
    (no-cache) forward log-probs; beams=1 degenerates to greedy."""
    cfg, weights, _ = gpt2_setup
    partition = [(1, 4), (5, 12)]
    pipe = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), max_len=32)
    ids = np.asarray(
        np.random.default_rng(51).integers(0, 100, size=(2, 6)), np.int64)

    got1 = np.asarray(pipe.generate_beam(ids, 6, beams=1))
    np.testing.assert_array_equal(got1, np.asarray(pipe.generate(ids, 6)))

    beams, steps = 3, 4
    got = np.asarray(pipe.generate_beam(ids, steps, beams=beams))

    # oracle: full forward per hypothesis, exact same beam semantics
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = gpt2_mod.load_params(cfg, sc, weights)
    from pipeedge_tpu.models.shard import make_shard_fn
    fn = make_shard_fn(gpt2_mod.FAMILY, cfg, sc)

    def logprobs(seqs):   # [N, S] -> [N, V] next-token log-probs
        logits = np.asarray(fn(params, jnp.asarray(seqs, jnp.int32)))
        x = logits[:, -1].astype(np.float64)
        x = x - x.max(axis=-1, keepdims=True)
        return x - np.log(np.exp(x).sum(axis=-1, keepdims=True))

    for b in range(ids.shape[0]):
        lp = logprobs(ids[b:b + 1])[0]
        order = np.argsort(-lp)[:beams]
        hyps = [(lp[t], [int(t)]) for t in order]
        for _ in range(steps - 1):
            seqs = np.stack([np.concatenate([ids[b], h[1]]) for h in hyps])
            lps = logprobs(seqs)
            cand = [(h[0] + lps[i][t], h[1] + [int(t)])
                    for i, h in enumerate(hyps) for t in range(cfg.vocab_size)]
            cand.sort(key=lambda c: -c[0])
            hyps = cand[:beams]
        np.testing.assert_array_equal(got[b, 6:], np.asarray(hyps[0][1]))


@pytest.mark.slow
def test_tp_decode_matches_plain(gpt2_setup):
    """Megatron tensor-parallel decode (head-sharded KV cache, 2 psums per
    block under shard_map) generates the same tokens as the single-device
    pipeline."""
    import jax
    from jax.sharding import Mesh
    cfg, weights, _ = gpt2_setup
    ids = np.asarray(
        np.random.default_rng(31).integers(0, 100, size=(2, 6)), np.int64)
    for partition in ([(1, 12)], [(1, 8), (9, 12)]):
        sp = _stage_params(cfg, partition, weights)
        plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                      max_len=24)
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        tp = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                   max_len=24, mesh=mesh)
        got_plain = np.asarray(plain.generate(ids, 8))
        got_tp = np.asarray(tp.generate(ids, 8))
        np.testing.assert_array_equal(got_tp, got_plain)

    # int8 KV composes with tp: the per-(position, head) scale rows carry
    # a head axis and shard over 'tp' with the K/V buffers, and each
    # device quantizes its own head slice with the same per-head math as
    # the unsharded int8 path — tokens match the single-device int8 run
    sp1 = _stage_params(cfg, [(1, 12)], weights)
    int8_plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, [(1, 12)],
                                       sp1, max_len=24, cache_bits=8)
    int8_tp = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, [(1, 12)], sp1, max_len=24, cache_bits=8,
        mesh=Mesh(np.array(jax.devices()[:2]), ("tp",)))
    np.testing.assert_array_equal(
        np.asarray(int8_tp.generate(ids, 8)),
        np.asarray(int8_plain.generate(ids, 8)))


@pytest.mark.slow
def test_sp_prefill_matches_plain(gpt2_setup):
    """Sequence-parallel prefill (causal ring attention over an 'sp' mesh,
    K/V all-gathered into the caches) + plain decode steps == the
    single-device pipeline, token for token."""
    import jax
    from jax.sharding import Mesh
    cfg, weights, _ = gpt2_setup
    ids = np.asarray(
        np.random.default_rng(61).integers(0, 100, size=(2, 8)), np.int64)
    for partition in ([(1, 12)], [(1, 8), (9, 12)]):
        sp = _stage_params(cfg, partition, weights)
        plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                      max_len=24)
        want = np.asarray(plain.generate(ids, 8))
        sp_mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        for kind in ("ring", "ulysses"):
            piped = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                          sp, max_len=24, sp_mesh=sp_mesh,
                                          sp_kind=kind)
            got = np.asarray(piped.generate(ids, 8))
            np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="not divisible by"):
        piped.generate(ids[:, :7], 4)
    with pytest.raises(ValueError, match="does not compose"):
        decode.DecodePipeline(gpt2_mod.FAMILY, cfg, [(1, 12)],
                              _stage_params(cfg, [(1, 12)], weights),
                              max_len=24, sp_mesh=sp_mesh, cache_bits=8)


@pytest.mark.fleet
def test_generate_cli(tmp_path):
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    for extra in ([], ["--kv-bits", "8"], ["--concurrent", "3"],
                  ["--beams", "2"]):
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "generate.py"),
             "-m", "pipeedge/test-tiny-gpt2", "-pt", "1,4,5,8", "-b", "2",
             "--prompt-len", "6", "--new-tokens", "5"] + extra,
            capture_output=True, env=env, cwd=str(tmp_path), text=True,
            timeout=300)
        assert proc.returncode == 0, proc.stderr
        assert "tok/s" in proc.stdout
        if extra[:1] == ["--concurrent"]:
            assert "continuous batching" in proc.stdout
        if extra[:1] == ["--beams"]:
            assert "beam 2" in proc.stdout   # CLI really ran beam search


@pytest.mark.fleet
def test_generate_dcn_matches_local(tmp_path):
    """Pipelined decoding across two OS processes over TCP produces the
    same greedy continuation as the local two-stage pipeline (shared
    weights file)."""
    import os
    import subprocess
    import sys

    from test_dcn_runtime import _run_fleet
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               DCN_CONNECT_TIMEOUT="20")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "save_model_weights.py"),
         "-m", "pipeedge/test-tiny-gpt2", "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    npz = str(tmp_path / "test-tiny-gpt2.npz")

    opts = ["-m", "pipeedge/test-tiny-gpt2", "-M", npz, "-pt", "1,4,5,8",
            "-b", "2", "--prompt-len", "6", "--new-tokens", "5"]
    local = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "generate.py")] + opts,
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert local.returncode == 0, local.stderr
    want = [l for l in local.stdout.splitlines() if "continuation" in l]
    assert want

    data, _, _ = _run_fleet(
        tmp_path, opts, world=2,
        env_extra={"JAX_PLATFORMS": "cpu", "DCN_CONNECT_TIMEOUT": "20"},
        script="tools/generate.py",
        rank_argv=lambda rank, world: ["--rank", str(rank)])
    assert data.returncode == 0, data.stdout + data.stderr
    got = [l for l in data.stdout.splitlines() if "continuation" in l]
    assert got == want, (got, want)
    assert "2 DCN ranks" in data.stdout

    # quantized stage edges (QuantPipe compression on the wire): the fleet
    # still decodes end-to-end (tokens may differ within quant error)
    data, _, _ = _run_fleet(
        tmp_path, opts + ["--edge-bits", "8"], world=2,
        env_extra={"JAX_PLATFORMS": "cpu", "DCN_CONNECT_TIMEOUT": "20",
                   "PIPEEDGE_NATIVE_QUANT": "0"},
        script="tools/generate.py",
        rank_argv=lambda rank, world: ["--rank", str(rank)])
    assert data.returncode == 0, data.stdout + data.stderr
    assert "2 DCN ranks" in data.stdout
    q_lines = [l for l in data.stdout.splitlines() if "continuation" in l]
    assert q_lines and q_lines[0].count(",") == 4  # 5 tokens emitted


@pytest.mark.fleet
def test_generate_dcn_adaptive_edge_quant(tmp_path):
    """VERDICT r2 item 6: the adaptive bitwidth policies steer decode DCN
    edges. ADAPTIVE_QUANT=HEURISTIC2 with an aggressive SEND_CONSTRAINT
    forces rank 0's output edge from raw (bit 0) down to the 2-bit floor
    after the first telemetry window; the consumer keeps decoding because
    the bitwidth rides the wire header (comm/wire.py), and the fleet still
    emits a full continuation."""
    import os
    import subprocess
    import sys

    from test_dcn_runtime import _run_fleet
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               DCN_CONNECT_TIMEOUT="20")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "save_model_weights.py"),
         "-m", "pipeedge/test-tiny-gpt2", "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    npz = str(tmp_path / "test-tiny-gpt2.npz")

    opts = ["-m", "pipeedge/test-tiny-gpt2", "-M", npz, "-pt", "1,4,5,8",
            "-b", "2", "--prompt-len", "6", "--new-tokens", "10"]
    data, _, _ = _run_fleet(
        tmp_path, opts, world=2,
        env_extra={"JAX_PLATFORMS": "cpu", "DCN_CONNECT_TIMEOUT": "20",
                   "PIPEEDGE_NATIVE_QUANT": "0",
                   # tokens/sec target far beyond a local 2-stage fleet:
                   # HEURISTIC2's transfer budget ~0 -> 2-bit floor
                   "ADAPTIVE_QUANT": "HEURISTIC2",
                   "SEND_CONSTRAINT": "1e9", "WINDOW_SIZE": "4"},
        script="tools/generate.py",
        rank_argv=lambda rank, world: ["--rank", str(rank)])
    assert data.returncode == 0, data.stdout + data.stderr
    assert "2 DCN ranks" in data.stdout
    # rank 0 (the data rank here) owns the adapted edge; the policy logs
    # each window decision via the runtime logger
    assert "Adaptive quantization (HEURISTIC2): bitwidth=2" in (
        data.stdout + data.stderr)
    lines = [l for l in data.stdout.splitlines() if "continuation" in l]
    assert lines and lines[0].count(",") == 9      # 10 tokens emitted


@pytest.mark.slow
def test_chunked_prefill_matches_whole(gpt2_setup):
    """prefill_ubatch pipelines the prompt pass in batch chunks; tokens
    must match the unchunked run exactly (dense model: routing-free)."""
    cfg, weights, _ = gpt2_setup
    partition = [(1, 4), (5, 12)]
    pipe = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), max_len=24)
    ids = np.asarray(
        np.random.default_rng(71).integers(0, 100, size=(4, 6)), np.int64)
    want = np.asarray(pipe.generate(ids, 7))
    got = np.asarray(pipe.generate(ids, 7, prefill_ubatch=2))
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="not divisible by"):
        pipe.generate(ids[:3], 4, prefill_ubatch=2)


def test_round_partition_to_blocks():
    """Sublayer-granular scheduler cuts round to block boundaries with
    coverage preserved (the profile->schedule->decode glue)."""
    r = decode.round_partition_to_blocks
    assert r([(1, 6), (7, 12)], 12) == [(1, 8), (9, 12)]
    assert r([(1, 5), (6, 7), (8, 12)], 12) == [(1, 4), (5, 8), (9, 12)]
    assert r([(1, 12)], 12) == [(1, 12)]
    # cuts collapsing onto the same boundary merge stages
    assert r([(1, 5), (6, 6), (7, 12)], 12) == [(1, 4), (5, 8), (9, 12)]
    assert r([(1, 1), (2, 2), (3, 12)], 12) == [(1, 4), (5, 12)]
    for part in (r([(1, 3), (4, 9), (10, 12)], 12),):
        covered = [x for l, rr in part for x in range(l, rr + 1)]
        assert covered == list(range(1, 13))
    with pytest.raises(ValueError, match="multiple of 4"):
        r([(1, 5)], 5)


def test_decode_validation_errors(gpt2_setup):
    cfg, weights, _ = gpt2_setup
    with pytest.raises(ValueError, match="block-aligned"):
        decode.make_stage_fns(gpt2_mod.FAMILY, cfg,
                              ShardConfig(1, 6, is_first=True, is_last=False))
    with pytest.raises(ValueError, match="contiguously cover"):
        decode.DecodePipeline(gpt2_mod.FAMILY, cfg, [(1, 4)],
                              _stage_params(cfg, [(1, 4)], weights),
                              max_len=8)
    with pytest.raises(ValueError, match="positions"):
        decode.DecodePipeline(gpt2_mod.FAMILY, cfg, [(1, 12)],
                              _stage_params(cfg, [(1, 12)], weights),
                              max_len=100)  # > max_position_embeddings=64
    partition = [(1, 12)]
    pipe = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), max_len=8)
    with pytest.raises(ValueError, match="exceeds max_len"):
        pipe.generate(np.zeros((1, 6), np.int64), new_tokens=4)
    # new_tokens=0 honors the [B, S + new_tokens] contract
    ids = np.zeros((1, 4), np.int64)
    assert np.asarray(pipe.generate(ids, 0)).shape == (1, 4)


@pytest.mark.slow
def test_bucketed_attend_crosses_buckets(gpt2_setup):
    """Bucketed decode-step attention (attend_bucket: static power-of-2
    windows instead of max_len) is token-identical to the full-window
    pipeline while the generation crosses several bucket boundaries
    (floor 4 -> buckets 4, 8, 16, 32 over a 28-token run), for both the
    f32 and the int8 cache, with HF generate as the external oracle."""
    import torch

    from pipeedge_tpu.parallel.decode import attend_bucket

    assert [attend_bucket(p, 64, 4) for p in (1, 4, 5, 9, 17, 33)] == \
        [4, 4, 8, 16, 32, 64]
    with pytest.raises(ValueError, match="exceeds"):
        attend_bucket(65, 64, 4)

    cfg, weights, model = gpt2_setup
    ids = np.asarray(
        np.random.default_rng(71).integers(0, 100, size=(2, 5)), np.int64)
    new = 28
    partition = [(1, 8), (9, 12)]
    sp = _stage_params(cfg, partition, weights)
    full = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                 max_len=64, attend_floor=64)
    bucketed = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                     max_len=64, attend_floor=4)
    want = np.asarray(full.generate(ids, new))
    np.testing.assert_array_equal(np.asarray(bucketed.generate(ids, new)),
                                  want)
    with torch.no_grad():
        hf = model.generate(torch.from_numpy(ids), max_new_tokens=new,
                            do_sample=False, pad_token_id=0).numpy()
    np.testing.assert_array_equal(want, hf)

    int8_full = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                      max_len=64, cache_bits=8,
                                      attend_floor=64)
    int8_bucketed = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                          sp, max_len=64, cache_bits=8,
                                          attend_floor=4)
    np.testing.assert_array_equal(
        np.asarray(int8_bucketed.generate(ids, new)),
        np.asarray(int8_full.generate(ids, new)))

    # tensor-parallel stages bucket too (shard_map closure re-bound per
    # static window; the position axis is unsharded) — f32 AND int8,
    # whose [B, T, H] scale rows truncate on the same position axis
    import jax
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    tp_bucketed = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition, sp, max_len=64, attend_floor=4,
        mesh=mesh)
    np.testing.assert_array_equal(np.asarray(tp_bucketed.generate(ids, new)),
                                  want)
    tp_int8_bucketed = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, partition, sp, max_len=64, attend_floor=4,
        cache_bits=8, mesh=mesh)
    np.testing.assert_array_equal(
        np.asarray(tp_int8_bucketed.generate(ids, new)),
        np.asarray(int8_full.generate(ids, new)))
