"""LLaMA family (RoPE / RMSNorm / SwiGLU / GQA) vs HF torch, through the
shard engine, pipeline splits, and the KV-cache decode subsystem."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import llama as llama_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.models.registry import get_model_config  # noqa: E402
from pipeedge_tpu.models.shard import make_shard_fn  # noqa: E402
from pipeedge_tpu.parallel import decode  # noqa: E402

MODEL = "pipeedge/test-tiny-llama"


@pytest.fixture(scope="module")
def llama_setup():
    from transformers import LlamaConfig, LlamaForCausalLM
    cfg = get_model_config(MODEL)
    hf_cfg = LlamaConfig(
        hidden_size=cfg.hidden_size, num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.kv_heads,
        intermediate_size=cfg.intermediate_size, vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.layer_norm_eps, rope_theta=cfg.rope_theta,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False)
    torch.manual_seed(11)
    model = LlamaForCausalLM(hf_cfg).eval()
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    return cfg, weights, model


def _stage_params(cfg, partition, weights):
    total = 4 * cfg.num_hidden_layers
    return [llama_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in partition]


def test_config_is_gqa():
    cfg = get_model_config(MODEL)
    assert cfg.kv_heads == 2 and cfg.num_attention_heads == 4


def test_forward_matches_hf(llama_setup):
    """Whole-model shard logits == HF LlamaForCausalLM logits (RoPE,
    RMSNorm, SwiGLU, and the 2-of-4 GQA head grouping all in play)."""
    cfg, weights, model = llama_setup
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = llama_mod.load_params(cfg, sc, weights)
    fn = make_shard_fn(llama_mod.FAMILY, cfg, sc)
    ids = np.random.default_rng(3).integers(0, cfg.vocab_size, size=(2, 9))
    got = np.asarray(fn(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("partition", [
    [(1, 4), (5, 8)],
    [(1, 3), (4, 8)],      # mid-block cut: 2-tuple (ctx, residual) edge
    [(1, 6), (7, 8)],      # mid-block cut at the MLP edge
])
def test_split_pipeline_matches_whole(llama_setup, partition):
    cfg, weights, model = llama_setup
    ids = np.random.default_rng(5).integers(0, cfg.vocab_size, size=(2, 7))
    data = jnp.asarray(ids, jnp.int32)
    total = 4 * cfg.num_hidden_layers
    for l, r in partition:
        sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
        params = llama_mod.load_params(cfg, sc, weights)
        data = make_shard_fn(llama_mod.FAMILY, cfg, sc)(params, data)
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(np.asarray(data), want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_greedy_decode_matches_hf_generate(llama_setup):
    """Pipelined KV-cache greedy decode == HF generate(do_sample=False):
    the GQA cache ([*, kv_heads, Dh]) and per-step RoPE rotation are
    exercised across a 2-stage partition."""
    cfg, weights, model = llama_setup
    partition = [(1, 4), (5, 8)]
    pipe = decode.DecodePipeline(
        llama_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), max_len=32)
    cache = decode.init_cache(cfg, 1, 2, 8)
    assert cache["k"].shape[3] == cfg.kv_heads    # GQA-sized cache
    ids = np.random.default_rng(7).integers(0, cfg.vocab_size, size=(2, 6))
    got = np.asarray(pipe.generate(ids, new_tokens=8))
    with torch.no_grad():
        want = model.generate(torch.from_numpy(ids), max_new_tokens=8,
                              do_sample=False, pad_token_id=0).numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_continuous_batching_and_wave_decode(llama_setup):
    """The llama family rides the serving stack unchanged: host continuous
    batching AND the SPMD wave decoder produce the same tokens as solo
    generate() via the family's cached_block_step/decode_embed hooks."""
    from jax.sharding import Mesh

    from pipeedge_tpu.parallel.batcher import ContinuousBatcher
    from pipeedge_tpu.parallel.spmd_decode import SpmdDecodePipeline
    cfg, weights, _ = llama_setup
    partition = [(1, 4), (5, 8)]
    stage_params = _stage_params(cfg, partition, weights)
    pipe = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, cfg.vocab_size, size=(1, 6))
               for _ in range(2)]
    solo = [np.asarray(pipe.generate(p, new_tokens=5)) for p in prompts]

    batcher = ContinuousBatcher(pipe)
    for i, p in enumerate(prompts):
        batcher.submit(i, p, new_tokens=5)
    results = batcher.run()
    for i in range(2):
        np.testing.assert_array_equal(results[i], solo[i])

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("stage",))
    wave = SpmdDecodePipeline(llama_mod.FAMILY, cfg, partition,
                              stage_params, mesh, max_len=32)
    got = np.asarray(wave.generate(np.stack(prompts), new_tokens=5))
    for i in range(2):
        np.testing.assert_array_equal(got[i], solo[i])


@pytest.mark.slow
def test_tp_block_and_spmd_tp_pipeline(llama_setup):
    """Megatron TP for llama (GQA column/row table + RoPE/SwiGLU body):
    a tp-sharded block matches the unsharded sublayer chain, and the
    pp x tp SPMD pipeline matches the single-shard forward. tp=2 leaves
    1 kv head per shard — the GQA grouping stays shard-local."""
    from jax.sharding import Mesh

    from pipeedge_tpu.parallel import spmd
    from pipeedge_tpu.parallel.tensor import (make_tp_block_fn,
                                              shard_block_params)
    cfg, weights, _ = llama_setup
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = llama_mod.load_params(cfg, sc, weights)
    bp = jax.tree_util.tree_map(lambda a: a[0], params["blocks"])
    x = np.random.default_rng(13).normal(size=(2, 9, 32)).astype(np.float32)
    data = jnp.asarray(x)
    for sub in range(4):
        data = llama_mod.sublayer(bp, sub, data, cfg)
    expected = np.asarray(data)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    fn = make_tp_block_fn(cfg, mesh)
    got = np.asarray(fn(shard_block_params(cfg, bp, mesh), jnp.asarray(x)))
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)

    partition = [(1, 4), (5, 8)]
    pipe_mesh = spmd.make_pipeline_mesh(2, tp=2)
    pipe = spmd.build_spmd_pipeline(
        llama_mod.FAMILY, cfg, partition,
        _stage_params(cfg, partition, weights), pipe_mesh)
    ids = np.random.default_rng(15).integers(0, cfg.vocab_size,
                                             size=(3, 2, 9))
    got = np.asarray(pipe.run(jnp.asarray(ids, jnp.int32)))
    whole = make_shard_fn(llama_mod.FAMILY, cfg, sc)
    expected = np.stack([np.asarray(whole(params, jnp.asarray(u, jnp.int32)))
                         for u in ids])
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)

    # tp DECODE: the family's tp cached step (RoPE on local heads, GQA
    # cache slice, vocab-sharded RMS head) is token-identical to the
    # single-device pipeline
    plain = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition,
                                  _stage_params(cfg, partition, weights),
                                  max_len=32)
    tp_pipe = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition,
                                    _stage_params(cfg, partition, weights),
                                    max_len=32, mesh=mesh)
    dec_ids = np.random.default_rng(17).integers(0, cfg.vocab_size,
                                                 size=(2, 6))
    np.testing.assert_array_equal(
        np.asarray(tp_pipe.generate(dec_ids, new_tokens=6)),
        np.asarray(plain.generate(dec_ids, new_tokens=6)))


@pytest.mark.slow
def test_beam_chunked_prefill_and_int8_compose(llama_setup):
    """The decode feature matrix is family-agnostic where it should be:
    beam search (width 1 == greedy), chunked prefill (token-identical),
    and the int8 GQA cache (close to exact) all run on llama unchanged."""
    cfg, weights, _ = llama_setup
    partition = [(1, 4), (5, 8)]
    sp = _stage_params(cfg, partition, weights)
    pipe = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                 max_len=32)
    ids = np.random.default_rng(19).integers(0, cfg.vocab_size, size=(4, 6))
    want = np.asarray(pipe.generate(ids, 6))
    np.testing.assert_array_equal(
        np.asarray(pipe.generate_beam(ids, 6, beams=1)), want)
    beam3 = np.asarray(pipe.generate_beam(ids, 4, beams=3))
    assert beam3.shape == (4, 10)
    np.testing.assert_array_equal(
        np.asarray(pipe.generate(ids, 6, prefill_ubatch=2)), want)

    int8 = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                 max_len=32, cache_bits=8)
    out8 = np.asarray(int8.generate(ids, 6))
    assert out8.shape == want.shape
    assert (out8[:, :6] == ids).all()
    # int8 error may flip late greedy picks on a random tiny model; the
    # first continuation token comes from exact (fresh-row) attention
    np.testing.assert_array_equal(out8[:, 6], want[:, 6])


def test_sp_refused(llama_setup):
    """RoPE makes chunk-local sp attention position-wrong; the FORWARD
    sp override refuses (the decode sp prefill instead pre-rotates at
    global chunk positions via the family hook — tested below)."""
    cfg, weights, _ = llama_setup
    with pytest.raises(NotImplementedError, match="RoPE|sequence"):
        llama_mod.sublayer({}, 0, jnp.zeros((1, 4, 32)), cfg,
                           attention_fn=lambda *a, **k: None)


@pytest.mark.slow
def test_sp_prefill_matches_plain(llama_setup):
    """Sequence-parallel llama prefill: RoPE at global chunk positions
    before the causal ring core, unrepeated post-RoPE GQA rows gathered
    into the cache — decode tokens match the single-device pipeline."""
    from jax.sharding import Mesh
    cfg, weights, _ = llama_setup
    partition = [(1, 4), (5, 8)]
    sp = _stage_params(cfg, partition, weights)
    plain = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                  max_len=32)
    sp_mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    piped = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                  max_len=32, sp_mesh=sp_mesh)
    ids = np.random.default_rng(23).integers(0, cfg.vocab_size, size=(2, 6))
    np.testing.assert_array_equal(
        np.asarray(piped.generate(ids, 6)),
        np.asarray(plain.generate(ids, 6)))


@pytest.mark.fleet
@pytest.mark.slow
def test_registry_roundtrip_and_cli(tmp_path):
    """save_model_weights --random -> npz -> factory logits; generate.py
    decodes the tiny llama end-to-end."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "save_model_weights.py"),
         "-m", MODEL, "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert os.path.exists(str(tmp_path / "test-tiny-llama.npz"))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "generate.py"),
         "-m", MODEL, "-M", "test-tiny-llama.npz", "-pt", "1,4,5,8",
         "-b", "2", "--prompt-len", "6", "--new-tokens", "5"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "tok/s" in proc.stdout
    # baseline continuation for the DCN comparison below (same args)
    want = [l for l in proc.stdout.splitlines() if "continuation" in l]
    assert want
    # the runtime drivers treat llama as any token model (host + spmd)
    for comm in ("host", "spmd"):
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "runtime.py"), "0", "2",
             "--platform", "cpu", "-m", MODEL, "-M", "test-tiny-llama.npz",
             "-pt", "1,4,5,8", "-b", "4", "-u", "2", "-c", comm],
            capture_output=True, env=env, cwd=str(tmp_path), text=True,
            timeout=300)
        assert proc.returncode == 0, proc.stderr
        assert "latency_sec=" in proc.stdout, (comm, proc.stdout)
    # DCN decode fleet (2 OS processes over TCP) == the local 2-stage
    # pipeline (the `want` baseline above), token for token — the family
    # dispatch covers the wire mode
    from test_dcn_runtime import _run_fleet
    opts = ["-m", MODEL, "-M", "test-tiny-llama.npz", "-pt", "1,4,5,8",
            "-b", "2", "--prompt-len", "6", "--new-tokens", "5"]
    data, _, _ = _run_fleet(
        tmp_path, opts, world=2,
        env_extra={"JAX_PLATFORMS": "cpu", "DCN_CONNECT_TIMEOUT": "20"},
        script="tools/generate.py",
        rank_argv=lambda rank, world: ["--rank", str(rank)])
    assert data.returncode == 0, data.stdout + data.stderr
    got = [l for l in data.stdout.splitlines() if "continuation" in l]
    assert got == want, (got, want)


@pytest.fixture(scope="module")
def mistral_setup():
    """Tiny Mistral: the llama block + sliding-window attention (window=4
    < prompt lengths used, so the mask is genuinely exercised)."""
    from transformers import MistralConfig, MistralForCausalLM
    cfg = get_model_config("pipeedge/test-tiny-mistral")
    hf_cfg = MistralConfig(
        hidden_size=cfg.hidden_size, num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.kv_heads,
        intermediate_size=cfg.intermediate_size, vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.layer_norm_eps, rope_theta=cfg.rope_theta,
        sliding_window=cfg.sliding_window, tie_word_embeddings=False,
        attn_implementation="eager")
    torch.manual_seed(13)
    model = MistralForCausalLM(hf_cfg).eval()
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    return cfg, weights, model


def test_mistral_forward_matches_hf(mistral_setup):
    """Sliding-window attention (Mistral): forward logits == HF with the
    window (4) well inside the sequence (9) — positions attend only to
    the last 4, so a full-causal mask would diverge."""
    cfg, weights, model = mistral_setup
    assert cfg.sliding_window == 4
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = llama_mod.load_params(cfg, sc, weights)
    fn = make_shard_fn(llama_mod.FAMILY, cfg, sc)
    ids = np.random.default_rng(29).integers(0, cfg.vocab_size, size=(2, 9))
    got = np.asarray(fn(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_mistral_greedy_decode_matches_hf_generate(mistral_setup):
    """KV-cache decode honors the sliding window at every step (absolute
    q_pos anchors the window over the masked cache) — tokens match HF
    generate across a 2-stage partition, with prompt+new tokens well past
    the window."""
    cfg, weights, model = mistral_setup
    partition = [(1, 4), (5, 8)]
    total = 4 * cfg.num_hidden_layers
    sp = [llama_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in partition]
    pipe = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                 max_len=32)
    ids = np.random.default_rng(31).integers(0, cfg.vocab_size, size=(2, 7))
    got = np.asarray(pipe.generate(ids, new_tokens=8))
    with torch.no_grad():
        want = model.generate(torch.from_numpy(ids), max_new_tokens=8,
                              do_sample=False, pad_token_id=0).numpy()
    np.testing.assert_array_equal(got, want)
    # tp decode applies the same window over the head-sharded cache
    from jax.sharding import Mesh
    tp_pipe = decode.DecodePipeline(
        llama_mod.FAMILY, cfg, partition, sp, max_len=32,
        mesh=Mesh(np.asarray(jax.devices()[:2]), ("tp",)))
    np.testing.assert_array_equal(
        np.asarray(tp_pipe.generate(ids, new_tokens=8)), got)
    # sp prefill binds the window into the ring core (global-position
    # anchored masks; out-of-window K/V blocks skipped) — token-identical
    # to the non-sp pipeline, which itself matched HF generate above
    for kind in ("ring", "ulysses"):
        sp_pipe = decode.DecodePipeline(
            llama_mod.FAMILY, cfg, partition, sp, max_len=32,
            sp_mesh=Mesh(np.asarray(jax.devices()[:2]), ("sp",)),
            sp_kind=kind)
        sp_got = np.asarray(sp_pipe.generate(ids[:, :6], new_tokens=8))
        want6 = np.asarray(pipe.generate(ids[:, :6], new_tokens=8))
        np.testing.assert_array_equal(sp_got, want6)


@pytest.mark.slow
def test_mistral_sp_prefill_long_prompt(mistral_setup):
    """Long-prompt windowed sp prefill: prompt length (16) is 4x the
    sliding window (4) over a 4-chip sp mesh (chunk=4), so whole K/V
    blocks fall outside every window (_ring_steps(4, 4, 4) == 2 of 4)
    and the ring must still be token-identical to the plain pipeline."""
    from pipeedge_tpu.parallel.sequence import _ring_steps
    cfg, weights, _ = mistral_setup
    assert _ring_steps(4, 4, cfg.sliding_window) == 2
    total = 4 * cfg.num_hidden_layers
    sp = [llama_mod.load_params(
        cfg, ShardConfig(1, total, is_first=True, is_last=True), weights)]
    pipe = decode.DecodePipeline(llama_mod.FAMILY, cfg, [(1, total)], sp,
                                 max_len=32)
    ids = np.random.default_rng(37).integers(0, cfg.vocab_size, size=(2, 16))
    want = np.asarray(pipe.generate(ids, new_tokens=6))
    from jax.sharding import Mesh
    sp_pipe = decode.DecodePipeline(
        llama_mod.FAMILY, cfg, [(1, total)], sp, max_len=32,
        sp_mesh=Mesh(np.asarray(jax.devices()[:4]), ("sp",)))
    got = np.asarray(sp_pipe.generate(ids, new_tokens=6))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_mistral_bucketed_attend_matches_full(mistral_setup):
    """Bucketed decode (static attend windows) composes with the llama
    family's cached step AND the sliding-window mask: tokens match the
    full-window pipeline across bucket boundaries."""
    cfg, weights, _ = mistral_setup
    partition = [(1, 4), (5, 8)]
    total = 4 * cfg.num_hidden_layers
    sp = [llama_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in partition]
    ids = np.random.default_rng(41).integers(0, cfg.vocab_size, size=(2, 5))
    full = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                 max_len=32, attend_floor=32)
    bucketed = decode.DecodePipeline(llama_mod.FAMILY, cfg, partition, sp,
                                     max_len=32, attend_floor=4)
    want = np.asarray(full.generate(ids, new_tokens=20))
    np.testing.assert_array_equal(
        np.asarray(bucketed.generate(ids, new_tokens=20)), want)
    # tp decode buckets through the family's tp_cached_block_step: the
    # GQA cache slice + window mask anchor over the truncated window
    from jax.sharding import Mesh
    tp_bucketed = decode.DecodePipeline(
        llama_mod.FAMILY, cfg, partition, sp, max_len=32, attend_floor=4,
        mesh=Mesh(np.asarray(jax.devices()[:2]), ("tp",)))
    np.testing.assert_array_equal(
        np.asarray(tp_bucketed.generate(ids, new_tokens=20)), want)
